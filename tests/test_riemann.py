"""Tests of the JAX-native Riemannian tangent-space baseline.

Closes the last partial SURVEY §2 row (component 30): the reference's
pyriemann tangent-space comparison (``notebooks/01_explore_data.ipynb``
cells 11-18) now has a TPU-native counterpart next to CSP+LDA.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from eegnetreplication_tpu.models.riemann import (  # noqa: E402
    riemannian_mean,
    tangent_features,
    tangent_lda_accuracy,
    tangent_lda_fit_predict,
    trial_covariances,
)
from test_csp import _oscillatory_data  # noqa: E402


def _random_spd(rng, n, c):
    a = rng.randn(n, c, c).astype(np.float32)
    return np.einsum("nij,nkj->nik", a, a) / c + 0.1 * np.eye(
        c, dtype=np.float32)


class TestCovariances:
    def test_spd_and_shapes(self):
        X, _ = _oscillatory_data(n_per_class=10)
        covs = np.asarray(trial_covariances(jnp.asarray(X)))
        assert covs.shape == (40, 8, 8)
        np.testing.assert_allclose(covs, np.swapaxes(covs, 1, 2), atol=1e-6)
        eigs = np.linalg.eigvalsh(covs)
        assert eigs.min() > 0  # shrinkage keeps them inside the SPD cone

    def test_short_window_still_spd(self):
        """T < C would make the raw covariance singular; shrinkage must
        keep the spectrum strictly positive."""
        rng = np.random.RandomState(0)
        X = rng.randn(5, 16, 8).astype(np.float32)  # 8 samples, 16 channels
        covs = np.asarray(trial_covariances(jnp.asarray(X)))
        assert np.linalg.eigvalsh(covs).min() > 0


class TestKarcherMean:
    def test_mean_of_identical_matrices_is_that_matrix(self):
        rng = np.random.RandomState(1)
        p = _random_spd(rng, 1, 6)[0]
        covs = jnp.asarray(np.stack([p] * 7))
        m = np.asarray(riemannian_mean(covs))
        np.testing.assert_allclose(m, p, rtol=1e-4, atol=1e-5)

    def test_commuting_case_is_geometric_mean(self):
        """For commuting (here: diagonal) SPD matrices the Karcher mean is
        the elementwise geometric mean — a closed form to pin against."""
        rng = np.random.RandomState(2)
        diags = rng.uniform(0.5, 2.0, size=(5, 4)).astype(np.float32)
        covs = jnp.asarray(np.stack([np.diag(d) for d in diags]))
        m = np.asarray(riemannian_mean(covs, n_iter=20))
        expected = np.diag(np.exp(np.log(diags).mean(axis=0)))
        np.testing.assert_allclose(m, expected, rtol=1e-4, atol=1e-5)

    def test_congruence_invariance(self):
        """mean(A P_i A^T) == A mean(P_i) A^T — the affine-invariant
        metric's defining property."""
        rng = np.random.RandomState(3)
        covs = _random_spd(rng, 6, 5)
        a = rng.randn(5, 5).astype(np.float32)
        a = a @ a.T + 0.5 * np.eye(5, dtype=np.float32)  # invertible
        m1 = np.asarray(riemannian_mean(
            jnp.asarray(np.einsum("ij,njk,lk->nil", a, covs, a)), n_iter=30))
        m0 = np.asarray(riemannian_mean(jnp.asarray(covs), n_iter=30))
        np.testing.assert_allclose(m1, a @ m0 @ a.T, rtol=2e-3, atol=2e-3)


class TestTangentSpace:
    def test_feature_dim_and_zero_at_reference(self):
        rng = np.random.RandomState(4)
        covs = jnp.asarray(_random_spd(rng, 10, 6))
        mean = riemannian_mean(covs)
        feats = np.asarray(tangent_features(covs, mean))
        assert feats.shape == (10, 6 * 7 // 2)
        # Projecting the reference point itself gives the zero vector.
        at_ref = np.asarray(tangent_features(mean[None], mean))
        np.testing.assert_allclose(at_ref, 0, atol=1e-4)

    def test_karcher_mean_centers_the_features(self):
        """At the Karcher mean the tangent vectors average to ~0 — the
        fixed-point condition itself, checked through the feature map."""
        rng = np.random.RandomState(5)
        covs = jnp.asarray(_random_spd(rng, 12, 5))
        feats = np.asarray(tangent_features(covs,
                                            riemannian_mean(covs, n_iter=30)))
        np.testing.assert_allclose(feats.mean(axis=0), 0, atol=1e-3)


class TestPipeline:
    def test_beats_chance_decisively(self):
        X, y = _oscillatory_data(n_per_class=60)
        n = len(y)
        acc = tangent_lda_accuracy(X[: n // 2], y[: n // 2],
                                   X[n // 2:], y[n // 2:])
        assert acc > 60.0  # chance is 25%

    def test_vmappable_over_folds(self):
        X, y = _oscillatory_data(n_per_class=20)
        half = len(y) // 2
        preds = jax.vmap(
            lambda a, b, c: tangent_lda_fit_predict(a, b, c)
        )(jnp.stack([jnp.asarray(X[:half])] * 2),
          jnp.stack([jnp.asarray(y[:half])] * 2),
          jnp.stack([jnp.asarray(X[half:])] * 2))
        assert preds.shape == (2, len(y) - half)
        assert bool(jnp.all(preds[0] == preds[1]))

    def test_prediction_values_in_range(self):
        X, y = _oscillatory_data(n_per_class=12)
        pred = tangent_lda_fit_predict(jnp.asarray(X), jnp.asarray(y),
                                       jnp.asarray(X))
        assert set(np.unique(np.asarray(pred))) <= {0, 1, 2, 3}
