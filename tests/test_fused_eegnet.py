"""Parity tests for the fused block-1 Pallas kernel (interpret mode on CPU).

The jnp reference path must match the flax model bit-for-bit-ish (same op
order), and the Pallas kernel must match the reference; together they pin the
algebraic refactoring (spatial-mix-first + folded BatchNorms) to the model's
eval-mode semantics.
"""

import unittest

import jax
import jax.numpy as jnp
import numpy as np

from eegnetreplication_tpu.models import EEGNet
from eegnetreplication_tpu.ops.fused_eegnet import (
    block1_pallas,
    block1_reference,
    fold_block1_params,
    fused_eval_forward,
)


def _setup(C=22, T=257, F1=8, D=2, seed=0, batch=8, perturb_bn=False):
    model = EEGNet(n_channels=C, n_times=T, F1=F1, D=D)
    v = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, C, T)),
                   train=False)
    if perturb_bn:
        # Non-trivial running stats: the folding must honour them.
        rng = np.random.RandomState(3)
        bs = jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.abs(rng.randn(*a.shape)) + 0.5),
            v["batch_stats"])
        v = {"params": v["params"], "batch_stats": bs}
    x = jnp.asarray(np.random.RandomState(seed + 1).randn(batch, C, T),
                    jnp.float32)
    return model, v, x


class TestFusedForward(unittest.TestCase):
    def test_fused_matches_flax_eval(self):
        model, v, x = _setup()
        want = model.apply(v, x, train=False)
        got = fused_eval_forward(model, v["params"], v["batch_stats"], x,
                                 use_pallas=False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5)

    def test_fused_matches_flax_with_perturbed_bn(self):
        model, v, x = _setup(perturb_bn=True)
        want = model.apply(v, x, train=False)
        got = fused_eval_forward(model, v["params"], v["batch_stats"], x,
                                 use_pallas=False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4)

    def test_wide_config(self):
        model, v, x = _setup(F1=16, D=4, batch=4)
        want = model.apply(v, x, train=False)
        got = fused_eval_forward(model, v["params"], v["batch_stats"], x,
                                 use_pallas=False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4)


class TestPallasKernel(unittest.TestCase):
    def _parity(self, **kw):
        model, v, x = _setup(**kw)
        S, W, A, B = fold_block1_params(v["params"], v["batch_stats"],
                                        eps=model.bn_epsilon)
        ref = block1_reference(x, S, W, A, B)
        out = block1_pallas(x, S, W, A, B, interpret=True)
        self.assertEqual(out.shape, ref.shape)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)

    def test_parity_default(self):
        self._parity()

    def test_parity_t256(self):
        self._parity(T=256, batch=4)

    def test_parity_wide(self):
        self._parity(F1=16, D=4, batch=2)

    def test_parity_perturbed_bn(self):
        self._parity(perturb_bn=True, batch=4)


class TestProductPathWiring(unittest.TestCase):
    """The fused forward must be what evaluate_pool/eval_step actually run."""

    def test_eval_step_matches_module_apply(self):
        from eegnetreplication_tpu.training.steps import (
            TrainState,
            eval_forward,
            eval_step,
            make_optimizer,
        )

        model, v, x = _setup(batch=6, perturb_bn=True)
        state = TrainState.create(v, make_optimizer())
        y = jnp.asarray(np.random.RandomState(9).randint(0, 4, 6))
        w = jnp.ones(6)

        logits_fused = eval_forward(model, v["params"], v["batch_stats"], x)
        logits_apply = model.apply(v, x, train=False)
        np.testing.assert_allclose(np.asarray(logits_fused),
                                   np.asarray(logits_apply),
                                   rtol=1e-4, atol=1e-5)
        loss, correct = jax.jit(
            lambda s, bx, by, bw: eval_step(model, s, bx, by, bw)
        )(state, x, y, w)
        self.assertTrue(np.isfinite(float(loss)))
        self.assertTrue(0 <= float(correct) <= 6)

    def test_escape_hatch_disables_fused(self):
        import os

        from eegnetreplication_tpu.ops.fused_eegnet import supports_fused_eval

        model, _, _ = _setup()
        self.assertTrue(supports_fused_eval(model))
        os.environ["EEGTPU_FUSED_EVAL"] = "0"
        try:
            self.assertFalse(supports_fused_eval(model))
        finally:
            del os.environ["EEGTPU_FUSED_EVAL"]

    def test_probe_is_false_off_tpu(self):
        from eegnetreplication_tpu.ops.fused_eegnet import probe_pallas

        model, _, _ = _setup()
        self.assertFalse(probe_pallas(model))  # CPU backend in tests


if __name__ == "__main__":
    unittest.main()
