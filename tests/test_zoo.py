"""Multi-tenant zoo (ISSUE 11): one-program vmap-stacked serving.

Covers the acceptance surface: stacked-vs-unstacked parity (fp32 exact,
int8 at the gate floor), gather-index permutation invariance, the
single-tenant degenerate case, per-tenant-per-channel stacked int8
quantization, the stack gate's refuse->per-model fallback, zoo
addressing (id / digest prefix / default), LRU evict + reload roundtrip
with ``model_load``/``model_evict``/``zoo_restack`` journaling, the
weighted-fair tenant dequeue's starvation bound, the zoo HTTP surface
(X-Model routing, /healthz tenants, per-tenant /reload), the fleet
membership tenant mirror, and the ``serve_bench.py --zoo`` selftest
floors plus the committed BENCH_ZOO.json acceptance record.
"""

import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from eegnetreplication_tpu.models import EEGNet  # noqa: E402
from eegnetreplication_tpu.obs import journal as obs_journal  # noqa: E402
from eegnetreplication_tpu.obs import schema  # noqa: E402
from eegnetreplication_tpu.ops import quant  # noqa: E402
from eegnetreplication_tpu.ops import stacked as ops_stacked  # noqa: E402
from eegnetreplication_tpu.serve.batcher import MicroBatcher  # noqa: E402
from eegnetreplication_tpu.serve.engine import (  # noqa: E402
    InferenceEngine,
)
from eegnetreplication_tpu.serve.registry import ModelZoo  # noqa: E402
from eegnetreplication_tpu.serve.zoo import (  # noqa: E402
    StackedEngine,
    build_stacked_engine,
    parse_zoo_spec,
    resolve_model_id,
    run_stack_gate,
)
from eegnetreplication_tpu.training.checkpoint import (  # noqa: E402
    save_checkpoint,
)

REPO = Path(__file__).resolve().parent.parent

C, T = 4, 64


def _variables(seed: int = 0):
    model = EEGNet(n_channels=C, n_times=T)
    variables = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, C, T)),
                           train=False)
    return model, variables["params"], variables["batch_stats"]


def _members(n: int = 3):
    return [(f"s{i + 1}", *_variables(i)) for i in range(n)]


def _checkpoint(tmp_path: Path, seed: int, name: str) -> Path:
    model, params, bs = _variables(seed)
    return save_checkpoint(
        tmp_path / name, params, bs,
        metadata={"model": "eegnet", "n_channels": C, "n_times": T,
                  "F1": model.F1, "D": model.D})


def _zoo_spec(tmp_path: Path, n: int = 3) -> dict:
    return {f"s{i + 1}": _checkpoint(tmp_path, i, f"s{i + 1}.npz")
            for i in range(n)}


@pytest.fixture(scope="module")
def trials():
    return np.random.RandomState(0).randn(40, C, T).astype(np.float32)


@pytest.fixture(scope="module")
def members():
    return _members(3)


@pytest.fixture(scope="module")
def stacked_fp32(members):
    return StackedEngine.from_members(members, buckets=(1, 8, 16))


class TestStackedOps:
    def test_stack_trees_roundtrip_via_tenant_slice(self, members):
        sp = ops_stacked.stack_trees([p for _, _, p, _ in members])
        for z, (_, _, p, _) in enumerate(members):
            got = ops_stacked.tenant_slice(sp, z)
            for (path, a), (_, b) in zip(
                    ops_stacked.tree_leaves_with_paths(got),
                    ops_stacked.tree_leaves_with_paths(p)):
                assert np.array_equal(a, np.asarray(b)), path

    def test_incongruent_trees_refuse_to_stack(self, members):
        other = EEGNet(n_channels=C + 1, n_times=T)
        v = other.init(jax.random.PRNGKey(9),
                       jnp.zeros((1, C + 1, T)), train=False)
        with pytest.raises(ValueError, match="not stackable"):
            ops_stacked.stack_trees([members[0][2], v["params"]])

    def test_stacked_quantization_is_per_tenant_per_channel(self, members):
        """The stacked int8 tree must carry each tenant's OWN scales:
        slicing tenant z out of the stacked quantization equals
        quantizing tenant z alone (up to the broadcast keepdims shape)."""
        sp = ops_stacked.stack_trees([p for _, _, p, _ in members])
        sq = quant.quantize_params(sp, stacked=True)
        for z, (_, _, p, _) in enumerate(members):
            alone = quant.quantize_params(p)
            sliced = ops_stacked.tenant_slice(sq, z)

            def walk(a, b, path=""):
                if quant.is_qleaf(a):
                    assert np.array_equal(a["q"], b["q"]), path
                    assert np.array_equal(
                        a["scale"],
                        np.asarray(b["scale"]).reshape(a["scale"].shape)
                    ), path
                    return
                if hasattr(a, "items"):
                    for k in a:
                        walk(a[k], b[k], f"{path}/{k}")
                    return
                # fp32 passthrough leaves (BN/bias) stack untouched.
                assert np.array_equal(np.asarray(a), np.asarray(b)), path

            walk(alone, sliced)


class TestStackedParity:
    def test_fp32_per_tenant_argmax_exact(self, members, stacked_fp32,
                                          trials):
        for z, (mid, model, p, b) in enumerate(members):
            ref = InferenceEngine(model, p, b, (16,)).infer(trials)
            got = stacked_fp32.infer(trials, np.full(len(trials), z,
                                                     np.int32))
            assert np.array_equal(got, ref), mid

    def test_int8_per_tenant_at_gate_floor(self, members, trials):
        int8 = StackedEngine.from_members(members, buckets=(16,),
                                          precision="int8")
        for z, (mid, model, p, b) in enumerate(members):
            tid = np.full(len(trials), z, np.int32)
            got = int8.infer(trials, tid)
            # Exact vs the standalone int8 engine (same quantization by
            # construction) ...
            alone = InferenceEngine(model, p, b, (16,), precision="int8")
            assert np.array_equal(got, alone.infer(trials)), mid
            # ... and within the quant-gate floor vs the fp32 reference.
            fp32 = InferenceEngine(model, p, b, (16,)).infer(trials)
            assert np.mean(got == fp32) >= 0.99, mid

    def test_gather_index_permutation_invariance(self, stacked_fp32,
                                                 trials):
        rng = np.random.RandomState(3)
        tid = rng.randint(0, 3, len(trials)).astype(np.int32)
        base = stacked_fp32.infer(trials, tid)
        perm = rng.permutation(len(trials))
        got = stacked_fp32.infer(trials[perm], tid[perm])
        assert np.array_equal(got, base[perm])

    def test_single_tenant_degenerate_case(self, members, trials):
        mid, model, p, b = members[0]
        one = StackedEngine.from_members([members[0]], buckets=(1, 16))
        ref = InferenceEngine(model, p, b, (1, 16)).infer(trials)
        assert np.array_equal(one.infer(trials, 0), ref)
        assert one.n_tenants == 1

    def test_tenant_index_out_of_range_raises(self, stacked_fp32, trials):
        with pytest.raises(ValueError, match="tenant index out of range"):
            stacked_fp32.infer(trials[:2], np.array([0, 3], np.int32))

    def test_scalar_tenant_broadcasts(self, stacked_fp32, members, trials):
        _, model, p, b = members[1]
        ref = InferenceEngine(model, p, b, (16,)).infer(trials[:5])
        assert np.array_equal(stacked_fp32.infer(trials[:5], 1), ref)


class TestStackGate:
    def test_pass_journals_stack_gate(self, members, stacked_fp32,
                                      trials, tmp_path):
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            refs = {mid: InferenceEngine(m, p, b, (16,))
                    for mid, m, p, b in members}
            gate = run_stack_gate(refs, stacked_fp32,
                                  [("t", trials[:16])], journal=jr)
            events = [e for e in schema.read_events(jr.events_path,
                                                    complete=False)
                      if e["event"] == "stack_gate"]
        assert gate.passed and gate.floor == 1.0
        assert set(gate.per_tenant) == {"s1", "s2", "s3"}
        assert all(v == 1.0 for v in gate.per_tenant.values())
        assert events and events[-1]["outcome"] == "pass"
        assert events[-1]["n_tenants"] == 3

    def test_mismatched_reference_refuses(self, members, stacked_fp32,
                                          trials):
        """A stack that disagrees with a tenant's reference must refuse —
        here simulated by handing tenant s1 ANOTHER model's reference."""
        _, m2, p2, b2 = members[1]
        refs = {mid: InferenceEngine(m, p, b, (16,))
                for mid, m, p, b in members}
        refs["s1"] = InferenceEngine(m2, p2, b2, (16,))
        gate = run_stack_gate(refs, stacked_fp32, [("t", trials[:16])])
        assert not gate.passed
        assert gate.per_tenant["s1"] < 1.0

    def test_build_refusal_returns_none(self, members, trials,
                                        monkeypatch):
        """A refused gate yields (None, gate) — the zoo then serves
        per-model (refuse-and-keep-serving)."""
        from eegnetreplication_tpu.serve import zoo as zoo_mod

        real = zoo_mod.run_stack_gate

        def refusing(refs, cand, gate_set=None, **kw):
            g = real(refs, cand, gate_set, **kw)
            return type(g)(outcome="refused", agreement=0.0,
                           per_tenant=g.per_tenant, floor=g.floor,
                           n_trials=g.n_trials, precision=g.precision)

        monkeypatch.setattr(zoo_mod, "run_stack_gate", refusing)
        engine, gate = build_stacked_engine(
            members, (16,), gate_set=[("t", trials[:8])])
        assert engine is None and not gate.passed


class TestZooAddressing:
    def test_parse_spec_pairs_and_errors(self, tmp_path):
        spec = parse_zoo_spec("a=/x/a.npz, b=/x/b.npz")
        assert list(spec) == ["a", "b"]
        with pytest.raises(ValueError, match="duplicate"):
            parse_zoo_spec("a=/x,a=/y")
        with pytest.raises(ValueError, match="id=path"):
            parse_zoo_spec("nonsense-without-equals")
        with pytest.raises(ValueError, match="no models"):
            parse_zoo_spec({})

    def test_parse_spec_directory(self, tmp_path):
        _zoo_spec(tmp_path, 2)
        spec = parse_zoo_spec(str(tmp_path))
        assert list(spec) == ["s1", "s2"]

    def test_resolve_rules(self):
        ids = ["s1", "s2"]
        digests = {"s1": "ab" * 32, "s2": "cd" * 32}
        assert resolve_model_id(ids, None, "s2", digests) == "s2"
        assert resolve_model_id(ids, "default", "s1", digests) == "s1"
        assert resolve_model_id(ids, "s2", "s1", digests) == "s2"
        assert resolve_model_id(ids, "abababab", "s1", digests) == "s1"
        with pytest.raises(KeyError, match="unknown model"):
            resolve_model_id(ids, "nope", "s1", digests)
        with pytest.raises(KeyError, match="ambiguous"):
            resolve_model_id(["a", "b"], "ee" * 8, "a",
                             {"a": "ee" * 32, "b": "ee" * 32})


class TestModelZoo:
    def test_stacked_matches_per_model_mixed_batch(self, tmp_path, trials):
        spec = _zoo_spec(tmp_path, 3)
        gate = [("g", trials[:16])]
        zs = ModelZoo(spec, buckets=(1, 8, 16), gate_set=gate, warm=False)
        zp = ModelZoo(spec, buckets=(1, 8, 16), gate_set=gate,
                      stack=False, warm=False)
        assert zs.stacked is not None and zp.stacked is None
        tid = np.random.RandomState(1).randint(0, 3, len(trials)) \
            .astype(np.int32)
        assert np.array_equal(zs.infer(trials, tid), zp.infer(trials, tid))

    def test_lru_evict_and_reload_roundtrip(self, tmp_path, trials):
        spec = _zoo_spec(tmp_path, 3)
        with obs_journal.run(tmp_path / "obs_lru", config={}) as jr:
            # Budget = one resident ladder: every materialization past
            # the first evicts the LRU sibling.
            zoo = ModelZoo(spec, buckets=(1, 16), stack=False,
                           max_programs=2, warm=False, journal=jr)
            before = {mid: zoo.infer(trials[:4], zoo.tenant_index(mid))
                      for mid in zoo.tenant_ids}
            snap = zoo.snapshot()
            assert snap["resident_programs"] <= 2
            resident = [t["engine_resident"] for t in snap["tenants"]]
            assert resident == [False, False, True]
            # An evicted tenant re-materializes on demand and serves the
            # SAME predictions (identity survives the evict/reload trip).
            again = zoo.infer(trials[:4], 0)
            assert np.array_equal(again, before["s1"])
            assert zoo.snapshot()["tenants"][0]["loads"] == 2
            events = schema.read_events(jr.events_path, complete=False)
        loads = [e for e in events if e["event"] == "model_load"]
        evicts = [e for e in events if e["event"] == "model_evict"]
        assert len(loads) == 4 and len(evicts) >= 2
        assert all(e["reason"] == "program_budget" for e in evicts)
        assert {e["model"] for e in loads} == {"s1", "s2", "s3"}

    def test_reload_restacks_and_journals(self, tmp_path, trials):
        spec = _zoo_spec(tmp_path, 2)
        new_ckpt = _checkpoint(tmp_path, 42, "s2_new.npz")
        gate = [("g", trials[:16])]
        with obs_journal.run(tmp_path / "obs_re", config={}) as jr:
            zoo = ModelZoo(spec, buckets=(1, 16), gate_set=gate,
                           warm=False, journal=jr)
            before = zoo.infer(trials, np.ones(len(trials), np.int32))
            old_digest = zoo.digest_for("s2")
            zoo.reload("s2", new_ckpt)
            after = zoo.infer(trials, np.ones(len(trials), np.int32))
            events = schema.read_events(jr.events_path, complete=False)
        assert zoo.digest_for("s2") != old_digest
        assert zoo.restacks == 2   # initial + reload
        assert not np.array_equal(before, after)  # new weights serve
        swaps = [e for e in events if e["event"] == "model_swap"]
        restacks = [e for e in events if e["event"] == "zoo_restack"]
        assert swaps and swaps[-1]["model"] == "s2"
        assert len(restacks) == 2
        assert restacks[-1]["outcome"] == "pass"
        assert restacks[-1]["reason"] == "reload:s2"

    def test_mixed_geometry_zoo_rejected(self, tmp_path, trials):
        """Every request shape-validates against ONE (C, T), so a
        mixed-geometry tenant could never be addressed — the zoo must
        fail fast with the separate-processes contract, not 400 that
        tenant's traffic forever."""
        spec = _zoo_spec(tmp_path, 1)
        other = EEGNet(n_channels=C + 3, n_times=T)
        v = other.init(jax.random.PRNGKey(8),
                       jnp.zeros((1, C + 3, T)), train=False)
        spec["wide"] = save_checkpoint(
            tmp_path / "wide.npz", v["params"], v["batch_stats"],
            metadata={"model": "eegnet", "n_channels": C + 3,
                      "n_times": T, "F1": other.F1, "D": other.D})
        with pytest.raises(ValueError, match="share one geometry"):
            ModelZoo(spec, buckets=(1, 16), warm=False,
                     gate_set=[("g", trials[:8])])

    def test_reload_rejects_geometry_change(self, tmp_path, trials):
        spec = _zoo_spec(tmp_path, 2)
        other = EEGNet(n_channels=C + 2, n_times=T)
        v = other.init(jax.random.PRNGKey(5),
                       jnp.zeros((1, C + 2, T)), train=False)
        bad = save_checkpoint(
            tmp_path / "bad_geo.npz", v["params"], v["batch_stats"],
            metadata={"model": "eegnet", "n_channels": C + 2,
                      "n_times": T, "F1": other.F1, "D": other.D})
        zoo = ModelZoo(spec, buckets=(1, 16),
                       gate_set=[("g", trials[:8])], warm=False)
        old = zoo.digest_for("s1")
        with pytest.raises(ValueError, match="geometry mismatch"):
            zoo.reload("s1", bad)
        assert zoo.digest_for("s1") == old  # serving state untouched

    def test_refused_restack_demotes_stale_stack(self, tmp_path, trials,
                                                 monkeypatch):
        """A reload whose follow-up restack is REFUSED must not leave the
        pre-reload stack serving under the new digest: the zoo demotes to
        per-model serving, and the reloaded tenant answers with its NEW
        weights."""
        from eegnetreplication_tpu.serve import zoo as zoo_mod

        spec = _zoo_spec(tmp_path, 2)
        gate = [("g", trials[:16])]
        with obs_journal.run(tmp_path / "obs_dem", config={}) as jr:
            zoo = ModelZoo(spec, buckets=(1, 16), gate_set=gate,
                           warm=False, journal=jr)
            assert zoo.stacked is not None
            fake_gate = zoo_mod.StackGateResult(
                outcome="refused", agreement=0.0, per_tenant={},
                floor=1.0, n_trials=0)
            monkeypatch.setattr(zoo_mod, "build_stacked_engine",
                                lambda *a, **k: (None, fake_gate))
            new_ckpt = _checkpoint(tmp_path, 55, "s2_demote.npz")
            zoo.reload("s2", new_ckpt)
            assert zoo.stacked is None   # demoted, not stale
            # The reloaded tenant serves its NEW weights via per-model
            # fallback (equal to a fresh engine over the new checkpoint).
            from eegnetreplication_tpu.serve.engine import (
                load_model_from_checkpoint,
            )

            m, p, b = load_model_from_checkpoint(new_ckpt)
            want = InferenceEngine(m, p, b, (1, 16)).infer(trials)
            got = zoo.infer(trials, np.ones(len(trials), np.int32))
            assert np.array_equal(got, want)
            events = schema.read_events(jr.events_path, complete=False)
        restacks = [e for e in events if e["event"] == "zoo_restack"]
        assert restacks[-1]["outcome"] == "refused"
        assert restacks[-1]["demoted_stale_stack"] is True

    def test_retune_rebuilds_stack_on_new_ladder(self, tmp_path, trials):
        zoo = ModelZoo(_zoo_spec(tmp_path, 2), buckets=(1, 16),
                       gate_set=[("g", trials[:8])], warm=False)
        before = zoo.infer(trials[:6], np.array([0, 1] * 3, np.int32))
        zoo.retune((1, 4, 8), warm=False)
        assert zoo.engine.buckets == (1, 4, 8)
        assert zoo.retunes == 1
        after = zoo.infer(trials[:6], np.array([0, 1] * 3, np.int32))
        assert np.array_equal(before, after)  # same weights, new ladder


class TestWeightedFairDequeue:
    def _batcher(self, infer, **kw):
        kw.setdefault("max_batch", 8)
        kw.setdefault("max_wait_ms", 1.0)
        kw.setdefault("max_queue_trials", 512)
        return MicroBatcher(infer, tenant_aware=True, **kw)

    def test_hot_tenant_cannot_starve_cold_one(self):
        """The starvation bound, asserted from dispatch order: a cold
        tenant's request submitted BEHIND a 50-request hot backlog must
        ride the very next dispatched batch."""
        dispatches = []
        gate = threading.Event()

        def infer(x, tenants):
            gate.wait(10)
            dispatches.append(sorted(set(tenants.tolist())))
            return np.asarray(tenants, np.int64)

        b = self._batcher(infer)
        x1 = np.zeros((1, C, T), np.float32)
        hot = [b.submit(x1, tenant=0) for _ in range(50)]
        cold = b.submit(x1, tenant=1)
        gate.set()
        assert cold.result(timeout=30)[0] == 1
        for f in hot:
            assert f.result(timeout=30)[0] == 0
        b.close()
        assert 1 in dispatches[0], dispatches[:3]
        # Bound restated: the cold request waited zero full dispatches.
        first_cold = next(i for i, d in enumerate(dispatches) if 1 in d)
        assert first_cold == 0

    def test_mixed_batch_scatter_per_tenant(self):
        """Each future must get ITS OWN rows back out of a mixed-tenant
        coalesced batch (the gather+forward+scatter contract)."""
        gate = threading.Event()

        def infer(x, tenants):
            gate.wait(10)
            return np.asarray(tenants, np.int64) * 100 + \
                np.asarray(x[:, 0, 0], np.int64)

        b = self._batcher(infer, max_batch=64)
        futs = []
        for i in range(12):
            tenant = i % 3
            x = np.full((1, C, T), float(i), np.float32)
            futs.append((tenant, i, b.submit(x, tenant=tenant)))
        gate.set()
        for tenant, i, fut in futs:
            assert fut.result(timeout=30)[0] == tenant * 100 + i
        b.close()

    def test_tenant_on_single_tenant_batcher_raises(self):
        b = MicroBatcher(lambda x: np.zeros(len(x), np.int64))
        with pytest.raises(ValueError, match="single-tenant"):
            b.submit(np.zeros((1, C, T), np.float32), tenant=2)
        b.close()

    def test_single_tenant_keeps_legacy_greedy_order(self):
        """tenant_aware with ONE tenant must coalesce exactly like the
        legacy FIFO+greedy scan (the [4,30,28] -> [32,30] regression).
        A blocker request parks the worker while the three queue up, so
        the coalesce sees them all regardless of scheduler timing."""
        first_started = threading.Event()
        release = threading.Event()
        sizes = []

        def infer(x, tenants):
            sizes.append(len(x))
            if len(sizes) == 1:  # only the blocker batch parks
                first_started.set()
                release.wait(10)
            return np.zeros(len(x), np.int64)

        b = self._batcher(infer, max_batch=32, max_wait_ms=0.0)
        try:
            b.submit(np.zeros((1, C, T), np.float32), tenant=0)
            assert first_started.wait(5)
            for n in (4, 30, 28):
                b.submit(np.zeros((n, C, T), np.float32), tenant=0)
            release.set()
            b.close(drain=True)
            assert sizes == [1, 32, 30]
        finally:
            release.set()
            b.close()


class TestZooHTTP:
    @pytest.fixture()
    def zoo_app(self, tmp_path, trials):
        from eegnetreplication_tpu.serve.service import ServeApp

        with obs_journal.run(tmp_path / "obs_http", config={}) as jr:
            app = ServeApp(zoo=_zoo_spec(tmp_path, 2), buckets=(1, 8),
                           max_wait_ms=1.0,
                           gate_set=[("g", trials[:8])], journal=jr)
            app.start()
            try:
                yield app, jr
            finally:
                app.stop()

    def _post(self, url, payload, headers=None, timeout=30):
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json", **(headers or {})})
        try:
            resp = urllib.request.urlopen(req, timeout=timeout)
            return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as exc:
            return exc.code, json.loads(exc.read())

    def test_model_addressing_and_healthz_tenants(self, zoo_app, trials):
        import urllib.request

        app, jr = zoo_app
        x = trials[:3].tolist()
        st, by_field = self._post(app.url + "/predict",
                                  {"trials": x, "model": "s2"})
        assert st == 200 and by_field["model"] == "s2"
        st, by_header = self._post(app.url + "/predict", {"trials": x},
                                   headers={"X-Model": "s2"})
        assert st == 200
        assert by_header["predictions"] == by_field["predictions"]
        st, default = self._post(app.url + "/predict", {"trials": x})
        assert st == 200 and default["model"] == "s1"
        assert by_field["model_digest"] == app.zoo.digest_for("s2")
        st, missing = self._post(app.url + "/predict",
                                 {"trials": x, "model": "zz"})
        assert st == 404 and "unknown model" in missing["error"]
        health = json.loads(urllib.request.urlopen(
            app.url + "/healthz", timeout=10).read())
        assert [t["model"] for t in health["tenants"]] == ["s1", "s2"]
        for t in health["tenants"]:
            assert t["resident"] is True      # stacked serves everyone
            assert t["digest"]
        assert health["zoo"]["stacked"]["n_tenants"] == 2
        assert health["zoo"]["default"] == "s1"

    def test_reload_one_tenant_restacks(self, zoo_app, tmp_path, trials):
        app, jr = zoo_app
        new_ckpt = _checkpoint(tmp_path, 77, "reload_target.npz")
        st, resp = self._post(app.url + "/reload",
                              {"model": "s2", "checkpoint": str(new_ckpt)})
        assert st == 200 and resp["model"] == "s2"
        assert resp["stacked"] is True and resp["zoo_restacks"] == 2
        st, after = self._post(app.url + "/predict",
                               {"trials": trials[:3].tolist(),
                                "model": "s2"})
        assert st == 200 and after["model_digest"] == resp["model_digest"]

    def test_session_windows_classify_under_default_tenant(self,
                                                           tmp_path):
        """A zoo server's streaming sessions must decide windows with
        the DEFAULT tenant's model (here s2 — NOT tenant 0), matching a
        single-model server over that same checkpoint byte for byte."""
        from eegnetreplication_tpu.serve.service import ServeApp

        spec = _zoo_spec(tmp_path, 2)
        rng = np.random.RandomState(9)
        chunk = rng.randn(C, T).astype(np.float32)

        def stream_decisions(app):
            app.start()
            try:
                st, opened = self._post(app.url + "/session/open",
                                        {"session": "sx", "hop": T,
                                         "ems_init_block_size": 16})
                assert st == 200, opened
                st, resp = self._post(
                    app.url + f"/session/{opened['session']}/samples",
                    {"samples": chunk.tolist()})
                assert st == 200, resp
                return [d["pred"] for d in resp["decisions"]]
            finally:
                app.stop()

        got = stream_decisions(ServeApp(
            zoo=spec, default_model="s2", buckets=(1, 8),
            max_wait_ms=1.0, gate_set=[("g", chunk[None])]))
        want = stream_decisions(ServeApp(
            spec["s2"], buckets=(1, 8), max_wait_ms=1.0))
        assert got and got == want

    def test_reload_without_checkpoint_repushes_own_file(self, zoo_app,
                                                         trials):
        """An omitted checkpoint re-pushes the NAMED tenant's own file —
        never another tenant's weights under its name."""
        app, jr = zoo_app
        before = app.zoo.digest_for("s2")
        st, resp = self._post(app.url + "/reload", {"model": "s2"})
        assert st == 200 and resp["model"] == "s2"
        assert resp["model_digest"] == before   # same weights, same id
        assert str(app.zoo.checkpoint_for("s2")) == resp["checkpoint"]


class TestZooTelemetry:
    def test_event_summary_zoo_fields(self):
        base = {"t": 1.0, "run_id": "r"}
        events = [
            dict(base, event="run_start", schema_version=1, git_sha="x",
                 platform="cpu", device_kind="cpu", n_devices=1,
                 config={}),
            dict(base, event="serve_start", checkpoint="c",
                 buckets=[1], max_batch=1, max_wait_ms=1.0,
                 tenants=["a", "b", "c"]),
            dict(base, event="model_load", model="a", digest="d1"),
            dict(base, event="model_evict", model="a",
                 reason="program_budget"),
            dict(base, event="zoo_restack", n_tenants=3, outcome="pass",
                 reason="initial"),
            dict(base, event="stack_gate", precision="fp32",
                 outcome="pass", agreement=1.0, floor=1.0, n_tenants=3),
            dict(base, event="run_end", status="ok", wall_s=1.0),
        ]
        schema.validate_events(events)
        out = schema.event_summary(events)
        assert out["tenants"] == 3
        assert out["model_loads"] == 1
        assert out["model_evictions"] == 1
        assert out["zoo_restacks"] == 1
        assert out["zoo_restack_outcome"] == "pass"
        assert out["stack_gate"] == "pass"
        assert out["stack_agreement"] == 1.0

    def test_fleet_membership_mirrors_tenant_count(self):
        from test_fleet import FakeReplica

        from eegnetreplication_tpu.serve.fleet import membership as ms

        fake = FakeReplica()
        try:
            fake.zoo = {"n_tenants": 9, "stacked": {"precision": "fp32"}}
            replica = ms.Replica("r1", fake.url,
                                 journal=obs_journal.NullJournal())
            m = ms.FleetMembership([replica],
                                   journal=obs_journal.NullJournal())
            m.poll_once()
            snap = replica.snapshot()
            assert snap["n_tenants"] == 9
            assert snap["stacked"] is True
            # A restart as a single-model server must RESET the mirror —
            # stale tenant state cannot linger in the fleet snapshot.
            fake.zoo = None
            m.poll_once()
            snap = replica.snapshot()
            assert snap["n_tenants"] is None
            assert snap["stacked"] is None
        finally:
            fake.stop()


@pytest.mark.filterwarnings("ignore")
class TestZooBenchSelftest:
    def test_selftest_passes(self, tmp_path):
        """The tier-1 --zoo selftest: stacked speedup floor over the
        per-model zoo, compiled-program count constant in tenants, gate
        verdicts consistent, zero drops through the restack-under-load
        leg."""
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "serve_bench.py"),
             "--zoo", "--selftest",
             "--zooRequests", "400",
             "--zooOut", str(tmp_path / "BENCH_ZOO.json"),
             "--workDir", str(tmp_path / "work")],
            capture_output=True, text=True, timeout=840,
            env={**dict(__import__("os").environ),
                 "JAX_PLATFORMS": "cpu",
                 "PYTHONPATH": str(REPO)})
        assert proc.returncode == 0, \
            f"stdout:\n{proc.stdout[-3000:]}\nstderr:\n{proc.stderr[-2000:]}"
        assert "SELFTEST PASS" in proc.stdout
        record = json.loads((tmp_path / "BENCH_ZOO.json").read_text())
        assert record["compiled_programs_constant_in_tenants"] is True
        assert record["restack_under_load"]["failures"] == 0


class TestCommittedZooArtifact:
    def test_committed_record_meets_acceptance(self):
        """The COMMITTED BENCH_ZOO.json must carry the ISSUE-11
        acceptance: 9 mixed tenants, stacked >= 3x the per-model zoo
        rps at unchanged per-tenant gate agreement, compiled-program
        count constant in tenants, zero drops through the restack leg."""
        record = json.loads((REPO / "BENCH_ZOO.json").read_text())
        assert record["n_tenants"] == 9
        assert record["stacked_speedup"] >= 3.0
        assert record["gate"]["outcome"] == "pass"
        assert all(v >= 1.0 for v in record["gate"]["per_tenant"].values())
        assert record["compiled_programs_constant_in_tenants"] is True
        assert record["stacked"]["compiled_programs"] == \
            len(record["buckets"])
        assert record["per_model"]["compiled_programs"] == \
            record["n_tenants"] * len(record["buckets"])
        for leg in ("per_model", "stacked", "restack_under_load"):
            assert record[leg]["failures"] == 0, leg
            assert record[leg]["completed"] == record[leg]["n_requests"]
        assert record["restack_under_load"]["restacks"] >= 2
        assert record["journal"]["zoo_restack_events"] >= 2
        assert record["journal"]["model_swap_events"] >= 1
