"""Fleet observability plane (ISSUE 16): journal rotation, the
incremental multi-journal aggregator, the eegtpu-top ops console, the
black-box prober, the POST /profile window, and the bench regression
sentinel.

The acceptance pin lives in :class:`TestOpsConsoleIntegration`: an
``eegtpu-top --json`` snapshot over a LIVE 3-replica fleet (real
ServeApps + real membership, each journaling its own run dir) plus a
cells-shaped three-level journal nest must agree with what ``/healthz``
and ``/metrics`` report from inside each replica.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from contextlib import ExitStack
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from eegnetreplication_tpu.models import EEGNet  # noqa: E402
from eegnetreplication_tpu.obs import journal as obs_journal  # noqa: E402
from eegnetreplication_tpu.obs import schema as obs_schema  # noqa: E402
from eegnetreplication_tpu.obs.agg import (  # noqa: E402
    Aggregator,
    FleetState,
    JournalTailer,
    discover_runs,
)
from eegnetreplication_tpu.obs.probe import PROBE_HEADER, Prober  # noqa: E402
from eegnetreplication_tpu.obs import top as obs_top  # noqa: E402
from eegnetreplication_tpu.training.checkpoint import (  # noqa: E402
    save_checkpoint,
)
from eegnetreplication_tpu.utils.flops import cost_flops_bytes  # noqa: E402

REPO = Path(__file__).resolve().parent.parent

C, T = 4, 64


def _checkpoint(tmp_path: Path, seed: int = 0, name: str = "m.npz") -> Path:
    model = EEGNet(n_channels=C, n_times=T)
    variables = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, C, T)),
                           train=False)
    return save_checkpoint(
        tmp_path / name, variables["params"], variables["batch_stats"],
        metadata={"model": "eegnet", "n_channels": C, "n_times": T,
                  "F1": model.F1, "D": model.D})


def _post_json(url: str, payload: dict, headers: dict | None = None,
               timeout: float = 30.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json", **(headers or {})})
    resp = urllib.request.urlopen(req, timeout=timeout)
    return resp.status, json.loads(resp.read())


def _get_json(url: str, timeout: float = 10.0) -> dict:
    return json.loads(urllib.request.urlopen(url, timeout=timeout).read())


def _probe_journal(tmp_path: Path, n: int, **journal_kw) -> obs_journal.RunJournal:
    """A journal with ``n`` sequence-stamped probe events (a declared
    type whose extra ``seq`` field survives round-trips)."""
    jr = obs_journal.RunJournal(tmp_path, **journal_kw)
    for i in range(n):
        jr.event("probe", status="ok", latency_ms=float(i), url="u", seq=i)
    return jr


# ---------------------------------------------------------------------------
# Satellite 1: size-triggered journal rotation.
# ---------------------------------------------------------------------------

class TestJournalRotation:
    def test_rollover_seals_segments_and_enforces_keep(self, tmp_path):
        jr = _probe_journal(tmp_path, 60, rotate_bytes=600, rotate_keep=3)
        live = jr.events_path
        # The 60th write may itself have sealed the live file; one more
        # event always lands in a (possibly fresh) live segment.
        jr.event("probe", status="ok", latency_ms=0.0, url="u", seq=60)
        assert live.exists()
        assert Path(f"{live}.1").exists()
        assert Path(f"{live}.3").exists()
        # keep-N: the oldest segment beyond the cap was unlinked.
        assert not Path(f"{live}.4").exists()
        # Every sealed segment ends at a line boundary.
        for seg in obs_schema.rotated_segments(live):
            assert seg.read_bytes().endswith(b"\n")

    def test_read_events_stitches_oldest_first(self, tmp_path):
        jr = _probe_journal(tmp_path, 60, rotate_bytes=600, rotate_keep=4)
        segments = obs_schema.rotated_segments(jr.events_path)
        # Oldest first means highest suffix first.
        suffixes = [int(s.name.rsplit(".", 1)[-1]) for s in segments]
        assert suffixes == sorted(suffixes, reverse=True)
        events = obs_schema.read_events(jr.events_path, complete=False)
        seqs = [e["seq"] for e in events if e["event"] == "probe"]
        # The stitched stream is the original order with only the OLDEST
        # prefix rotated away — contiguous and ending at the last write.
        assert seqs == list(range(seqs[0], 60))
        assert seqs[-1] == 59 and seqs[0] > 0

    def test_nonpositive_rotate_bytes_disables_rotation(self, tmp_path):
        jr = _probe_journal(tmp_path, 60, rotate_bytes=0)
        assert obs_schema.rotated_segments(jr.events_path) == []
        events = obs_schema.read_events(jr.events_path, complete=False)
        assert sum(1 for e in events if e["event"] == "probe") == 60

    def test_env_override_configures_rotation(self, tmp_path, monkeypatch):
        monkeypatch.setenv("EEGTPU_JOURNAL_ROTATE_BYTES", "600")
        monkeypatch.setenv("EEGTPU_JOURNAL_ROTATE_KEEP", "2")
        jr = _probe_journal(tmp_path, 60)
        assert Path(f"{jr.events_path}.1").exists()
        assert Path(f"{jr.events_path}.2").exists()
        assert not Path(f"{jr.events_path}.3").exists()

    def test_persistent_handle_keeps_writing_after_rollover(self, tmp_path):
        """The persistent append handle must follow the rename: events
        after a rollover land in the FRESH live file, not the sealed
        segment the old file descriptor still points at."""
        jr = _probe_journal(tmp_path, 40, rotate_bytes=600, rotate_keep=8)
        jr.event("probe", status="ok", latency_ms=0.0, url="u", seq=999)
        tail = jr.events_path.read_text().strip().splitlines()[-1]
        assert json.loads(tail)["seq"] == 999


# ---------------------------------------------------------------------------
# Tentpole 1: the incremental journal tailer + aggregator.
# ---------------------------------------------------------------------------

class TestJournalTailer:
    def _run_dir(self, tmp_path, lines):
        d = tmp_path / "run"
        d.mkdir(exist_ok=True)
        (d / "events.jsonl").write_text("".join(lines))
        return d

    def test_torn_live_tail_held_back_then_completed(self, tmp_path):
        whole = json.dumps({"event": "probe", "t": 1.0, "seq": 0}) + "\n"
        torn = json.dumps({"event": "probe", "t": 2.0, "seq": 1})
        d = self._run_dir(tmp_path, [whole, torn[:10]])
        tailer = JournalTailer(d)
        events = tailer.poll()
        assert [e["seq"] for e in events] == [0]
        assert tailer.dropped == 0
        # The cursor held at the line boundary; re-polling the still-torn
        # tail yields nothing and loses nothing.
        assert tailer.poll() == []
        with open(d / "events.jsonl", "a") as fh:
            fh.write(torn[10:] + "\n")
        assert [e["seq"] for e in tailer.poll()] == [1]

    def test_rotation_drain_reads_sealed_segment(self, tmp_path):
        line = [json.dumps({"event": "probe", "t": float(i), "seq": i})
                + "\n" for i in range(4)]
        d = self._run_dir(tmp_path, line[:2])
        tailer = JournalTailer(d)
        assert [e["seq"] for e in tailer.poll()] == [0, 1]
        # Rotate under the tailer: unread bytes move to the sealed .1 and
        # the live file restarts SMALLER than the cursor — the tailer's
        # rotation signal.
        (d / "events.jsonl").write_text(line[0] + line[1] + line[2])
        os.replace(d / "events.jsonl", d / "events.jsonl.1")
        (d / "events.jsonl").write_text(line[3])
        assert [e["seq"] for e in tailer.poll()] == [2, 3]
        assert tailer.dropped == 0

    def test_sealed_torn_tail_is_counted_dropped(self, tmp_path):
        line = json.dumps({"event": "probe", "t": 0.0, "seq": 0}) + "\n"
        d = self._run_dir(tmp_path, [line])
        tailer = JournalTailer(d)
        tailer.poll()
        # The sealed segment ends torn (crash mid-rotation): that tail
        # can never complete — it must be counted, not re-polled forever.
        (d / "events.jsonl.1").write_text(line + '{"event": "pro')
        (d / "events.jsonl").write_text("")
        assert tailer.poll() == []
        assert tailer.dropped == 1

    def test_unparseable_complete_line_skipped_and_counted(self, tmp_path):
        good = json.dumps({"event": "probe", "t": 0.0, "seq": 0}) + "\n"
        d = self._run_dir(tmp_path, [good, "not json\n", good])
        tailer = JournalTailer(d)
        assert len(tailer.poll()) == 2
        assert tailer.dropped == 1


class TestAggregator:
    def _write_run(self, run_dir: Path, events: list[dict]) -> None:
        run_dir.mkdir(parents=True, exist_ok=True)
        with open(run_dir / "events.jsonl", "w") as fh:
            for ev in events:
                fh.write(json.dumps(ev) + "\n")

    def test_discover_runs_at_any_depth(self, tmp_path):
        ev = [{"event": "run_start", "t": 1.0}]
        self._write_run(tmp_path / "a" / "run1", ev)
        self._write_run(tmp_path / "a" / "run1" / "replica_obs" / "r1", ev)
        # The cells shape: THREE levels below the root.
        deep = (tmp_path / "a" / "front" / "c0_obs" / "cell"
                / "replica_obs" / "rep")
        self._write_run(deep, ev)
        # A fully rotated run (live file gone, only sealed segments).
        rotated = tmp_path / "a" / "old"
        rotated.mkdir()
        (rotated / "events.jsonl.1").write_text(json.dumps(ev[0]) + "\n")
        runs = discover_runs([tmp_path / "a"])
        assert {r.name for r in runs} == {"run1", "r1", "rep", "old"}
        # Deterministic: a repeat discovery yields the same order.
        assert runs == discover_runs([tmp_path / "a"])

    def test_cursor_resume_skips_history(self, tmp_path):
        run = tmp_path / "root" / "run1"
        now = time.time()
        self._write_run(run, [{"event": "request", "t": now, "status": "ok",
                               "latency_ms": 1.0} for _ in range(5)])
        first = Aggregator([tmp_path / "root"])
        snap = first.poll()
        assert snap["runs"][0]["n_events"] == 5
        cursors = first.cursors()
        assert cursors[str(run)] > 0
        with open(run / "events.jsonl", "a") as fh:
            fh.write(json.dumps({"event": "request", "t": now,
                                 "status": "ok", "latency_ms": 2.0}) + "\n")
        # A RESTARTED aggregator seeded with the old cursors folds only
        # the new tail — history is not replayed into fresh windows.
        resumed = Aggregator([tmp_path / "root"])
        resumed.seed_cursors(cursors)
        snap = resumed.poll()
        assert snap["runs"][0]["n_events"] == 1
        assert snap["runs"][0]["total_requests"] == 1

    def test_poll_journals_agg_snapshot(self, tmp_path):
        self._write_run(tmp_path / "root" / "run1",
                        [{"event": "fleet_member", "t": time.time(),
                          "replica": "r0", "state": "live"}])
        with obs_journal.run(tmp_path / "own_obs", config={}) as jr:
            agg = Aggregator([tmp_path / "root"], journal=jr)
            snap = agg.poll()
        assert snap["n_runs"] == 1 and snap["n_members"] == 1
        events = obs_schema.read_events(jr.events_path)
        snaps = [e for e in events if e["event"] == "agg_snapshot"]
        assert snaps and snaps[0]["n_runs"] == 1
        assert snaps[0]["n_members"] == 1
        assert snaps[0]["window_s"] == agg.window_s


class TestFleetStateFold:
    def test_rolling_fold_rates_quantiles_members(self, tmp_path):
        state = FleetState(window_s=60.0, clock=lambda: 100.0)
        reqs = [{"event": "request", "t": 90.0 + i, "status": "ok",
                 "latency_ms": float(i + 1), "model": "m0"}
                for i in range(10)]
        state.fold("runA", [
            {"event": "run_start", "t": 90.0, "run_id": "ra",
             "platform": "cpu"},
            {"event": "serve_start", "t": 90.0},
            *reqs,
            {"event": "request", "t": 99.0, "status": "error",
             "latency_ms": 3.0},
            {"event": "fleet_member", "t": 99.0, "replica": "r0",
             "state": "live"},
            {"event": "probe", "t": 99.0, "status": "ok",
             "latency_ms": 2.0, "url": "u"},
            {"event": "probe", "t": 99.5, "status": "timeout",
             "latency_ms": 500.0, "url": "u"},
        ])
        state.fold("runB", [
            {"event": "slo_breach", "t": 99.0, "objective": "probe:avail"},
            {"event": "request", "t": 30.0, "status": "ok",
             "latency_ms": 1.0},  # older than the 60 s window: pruned
        ])
        snap = state.snapshot()
        assert snap["n_runs"] == 2 and snap["n_members"] == 1
        assert snap["members"]["r0"]["state"] == "live"
        assert snap["slo_breached"] == ["probe:avail"]
        run_a = next(r for r in snap["runs"] if r["dir"] == "runA")
        assert run_a["role"] == "serve" and run_a["run_id"] == "ra"
        assert run_a["total_requests"] == 11
        assert run_a["window_requests"] == 11
        # 11 requests over the 10 s between the first in-window request
        # and the frozen clock.
        assert run_a["rps"] == pytest.approx(1.1)
        assert run_a["p50_ms"] == pytest.approx(5.5, abs=1.0)
        assert run_a["window_non_ok"] == 1
        assert run_a["tenants"] == {"m0": 10}
        assert run_a["probes"] == {"window": 2, "failures": 1,
                                   "p95_ms": 2.0}
        run_b = next(r for r in snap["runs"] if r["dir"] == "runB")
        assert run_b["window_requests"] == 0  # pruned
        assert run_b["total_requests"] == 1   # lifetime count survives


# ---------------------------------------------------------------------------
# Tentpole 3: the black-box prober (stub front door for determinism).
# ---------------------------------------------------------------------------

class _StubFront:
    """A minimal /healthz + /predict front door with scriptable answers."""

    def __init__(self):
        self.preds = [2]
        self.fail_code = None
        self.probe_headers_seen = []
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: A003 — quiet
                pass

            def _reply(self, payload, code=200):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                stub.probe_headers_seen.append(
                    self.headers.get(PROBE_HEADER))
                self._reply({"status": "ok",
                             "geometry": {"n_channels": C, "n_times": T}})

            def do_POST(self):  # noqa: N802
                stub.probe_headers_seen.append(
                    self.headers.get(PROBE_HEADER))
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                if stub.fail_code:
                    self._reply({"error": "down"}, code=stub.fail_code)
                else:
                    self._reply({"predictions": list(stub.preds)})

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)
        self.thread.start()
        self.url = "http://127.0.0.1:%d" % self.server.server_address[1]

    def stop(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture
def stub_front():
    stub = _StubFront()
    try:
        yield stub
    finally:
        stub.stop()


class TestProber:
    def test_known_answer_pins_then_mismatch(self, stub_front, tmp_path):
        with obs_journal.run(tmp_path, config={}) as jr:
            prober = Prober(stub_front.url, slo=None, journal=jr)
            assert prober.probe_once()["status"] == "ok"
            assert prober.probe_once()["status"] == "ok"
            # The model starts answering differently: wrong-answer gray
            # failure, distinct from unreachability.
            stub_front.preds = [3]
            assert prober.probe_once()["status"] == "mismatch"
            # A deliberate swap re-pins on the next success.
            prober.reset_expected()
            assert prober.probe_once()["status"] == "ok"
            assert prober.probe_once()["status"] == "ok"
        # Every canary was tagged so the server can exempt it.
        assert all(h == "1" for h in stub_front.probe_headers_seen)
        events = obs_schema.read_events(jr.events_path)
        probes = [e for e in events if e["event"] == "probe"]
        assert [e["status"] for e in probes] \
            == ["ok", "ok", "mismatch", "ok", "ok"]
        for e in probes:
            assert e["url"] == stub_front.url
            assert e["latency_ms"] >= 0.0

    def test_unavailability_breaches_probe_slo(self, stub_front, tmp_path):
        with obs_journal.run(tmp_path, config={}) as jr:
            prober = Prober(stub_front.url, slo="availability>0.99",
                            min_samples=3, journal=jr)
            stub_front.fail_code = 500
            for _ in range(3):
                assert prober.probe_once()["status"] == "http_500"
            state = prober.state()
            assert state["breached"] and prober.breached
            assert state["probes_sent"] == 3
            # Recovery: the front door heals, the window refills with
            # successes until availability clears the threshold again.
            stub_front.fail_code = None
            prober.reset_expected()
            for _ in range(300):
                prober.probe_once()
                if not prober.breached:
                    break
            assert not prober.breached
        events = obs_schema.read_events(jr.events_path)
        breaches = [e for e in events if e["event"] == "slo_breach"]
        assert len(breaches) == 1
        # The probe: prefix keeps outside-in breaches distinct from the
        # server-side monitor's objectives.
        assert breaches[0]["objective"].startswith("probe:")
        assert breaches[0]["metric"] == "probe_availability"
        recoveries = [e for e in events if e["event"] == "slo_recovered"]
        assert len(recoveries) == 1
        assert recoveries[0]["objective"] == breaches[0]["objective"]

    def test_unreachable_front_door_is_error_not_crash(self, tmp_path):
        with obs_journal.run(tmp_path, config={}) as jr:
            prober = Prober("http://127.0.0.1:9", timeout_s=0.5,
                            slo=None, journal=jr)
            assert prober.probe_once()["status"] == "error"
        events = obs_schema.read_events(jr.events_path)
        assert [e["status"] for e in events if e["event"] == "probe"] \
            == ["error"]


# ---------------------------------------------------------------------------
# FLOPs attribution on compile events (tentpole 4, engine/training side).
# ---------------------------------------------------------------------------

class TestCostAttribution:
    def test_cost_flops_bytes_reads_cost_analysis_shapes(self):
        class _Lowered:
            def __init__(self, analysis):
                self._analysis = analysis

            def cost_analysis(self):
                return self._analysis

        assert cost_flops_bytes(
            _Lowered({"flops": 5.0, "bytes accessed": 3.0})) == (5.0, 3.0)
        # Older jax returns a one-element list of dicts.
        assert cost_flops_bytes(
            _Lowered([{"flops": 7.0}])) == (7.0, None)
        # NaN / non-positive / missing keys degrade to None, never raise.
        assert cost_flops_bytes(
            _Lowered({"flops": float("nan")})) == (None, None)
        assert cost_flops_bytes(_Lowered(None)) == (None, None)
        assert cost_flops_bytes(object()) == (None, None)

    def test_cost_flops_bytes_on_real_lowering(self):
        lowered = jax.jit(lambda x: x @ x).lower(
            np.zeros((8, 8), np.float32))
        flops, nbytes = cost_flops_bytes(lowered)
        # CPU cost analysis reports flops for a matmul; bytes accessed is
        # backend-dependent — both must at least be well-typed.
        for v in (flops, nbytes):
            assert v is None or v > 0
        assert flops is not None and flops >= 2 * 8 * 8 * 8 * 0.5

    def test_compile_events_carry_flops_fields(self, tmp_path):
        from eegnetreplication_tpu.serve.engine import InferenceEngine
        with obs_journal.run(tmp_path, config={}) as jr:
            InferenceEngine.from_checkpoint(_checkpoint(tmp_path),
                                            buckets=(1,), journal=jr)
        events = obs_schema.read_events(jr.events_path)
        compiles = [e for e in events if e["event"] == "compile"]
        assert compiles
        for e in compiles:
            # Attribution is best-effort (None where the backend withholds
            # cost analysis) but the fields must ride on every compile.
            assert "flops" in e and "bytes_accessed" in e
            assert e["flops"] is None or e["flops"] > 0


# ---------------------------------------------------------------------------
# Tentpole 3+4 against a REAL replica: probe exemption and POST /profile.
# ---------------------------------------------------------------------------

@pytest.fixture(scope="class")
def live_app(tmp_path_factory):
    from eegnetreplication_tpu.serve.service import ServeApp

    root = tmp_path_factory.mktemp("live_app")
    ck = _checkpoint(root)
    with obs_journal.run(root / "obs", config={}) as jr:
        app = ServeApp(ck, port=0, buckets=(1, 4), max_wait_ms=1.0,
                       journal=jr).start()
        try:
            yield app, jr
        finally:
            app.stop()


class TestProbeExemption:
    def test_probe_requests_segregated_from_user_stats(self, live_app):
        app, jr = live_app
        x = np.random.RandomState(3).randn(1, C, T).astype(np.float32)
        before = _get_json(app.url + "/metrics")
        code, resp = _post_json(app.url + "/predict",
                                {"trials": x.tolist()},
                                headers={PROBE_HEADER: "1"})
        assert code == 200 and len(resp["predictions"]) == 1
        code, _ = _post_json(app.url + "/predict", {"trials": x.tolist()})
        assert code == 200
        after = _get_json(app.url + "/metrics")

        def count(m, name):
            return sum(c["value"] for c in m["counters"].get(name, []))

        # The canary landed in probe_requests_total; user accounting
        # (requests_total, the latency histogram the SLO monitor reads)
        # moved by exactly the ONE user request.
        assert count(after, "probe_requests_total") \
            == count(before, "probe_requests_total") + 1
        assert count(after, "requests_total") \
            == count(before, "requests_total") + 1

    def test_prober_end_to_end_against_real_replica(self, live_app):
        app, jr = live_app
        prober = Prober(app.url, slo=None, journal=jr, timeout_s=30.0)
        assert prober.probe_once()["status"] == "ok"
        # Deterministic forward: the pinned answer holds on a re-probe.
        assert prober.probe_once()["status"] == "ok"

    def test_probe_marked_in_request_events(self, live_app):
        app, jr = live_app
        x = np.random.RandomState(5).randn(1, C, T).astype(np.float32)
        code, _ = _post_json(app.url + "/predict", {"trials": x.tolist()},
                             headers={PROBE_HEADER: "1"})
        assert code == 200
        events = obs_schema.read_events(jr.events_path, complete=False)
        probe_reqs = [e for e in events
                      if e["event"] == "request" and e.get("probe")]
        assert probe_reqs
        assert all(e["status"] == "ok" for e in probe_reqs)


class TestProfileEndpoint:
    def test_window_lifecycle_202_409_400(self, live_app):
        app, jr = live_app
        # Malformed bodies are 400, not windows.
        for bad in ({"seconds": -1}, {"seconds": "soon"},
                    {"log_dir": 7}, []):
            with pytest.raises(urllib.error.HTTPError) as err:
                _post_json(app.url + "/profile", bad)
            assert err.value.code == 400
        from eegnetreplication_tpu.serve.service import PROFILE_MAX_S
        code, resp = _post_json(app.url + "/profile", {"seconds": 0.3})
        assert code == 202 and resp["status"] == "started"
        assert resp["seconds"] == pytest.approx(0.3)
        assert resp["max_s"] == PROFILE_MAX_S and resp["log_dir"]
        # One window at a time: a concurrent request is refused, the
        # running window is untouched.
        with pytest.raises(urllib.error.HTTPError) as err:
            _post_json(app.url + "/profile", {"seconds": 0.3})
        assert err.value.code == 409
        deadline = time.time() + 30.0
        window = None
        while time.time() < deadline and window is None:
            time.sleep(0.1)
            events = obs_schema.read_events(jr.events_path,
                                            complete=False)
            done = [e for e in events if e["event"] == "profile_window"]
            window = done[-1] if done else None
        assert window is not None, "profile_window never journaled"
        assert window["status"] == "ok"
        assert window["dur_s"] >= 0.3
        assert window["log_dir"] == resp["log_dir"]
        # The bounded window released the slot: a new one is accepted.
        code, resp2 = _post_json(app.url + "/profile",
                                 {"seconds": 0.1,
                                  "log_dir": resp["log_dir"] + "_b"})
        assert code == 202
        assert resp2["log_dir"].endswith("_b")

    def test_requested_seconds_clamped_to_max(self, live_app, monkeypatch):
        from eegnetreplication_tpu.serve import service as serve_service
        app, _ = live_app
        # Clamp a huge request to a SMALL ceiling so the resulting window
        # cannot outlive this test (the real ceiling is 60 s).
        monkeypatch.setattr(serve_service, "PROFILE_MAX_S", 0.2)
        deadline = time.time() + 30.0
        while time.time() < deadline:
            try:
                code, resp = _post_json(app.url + "/profile",
                                        {"seconds": 10_000.0})
            except urllib.error.HTTPError as err:
                assert err.code == 409  # previous test's window draining
                time.sleep(0.1)
                continue
            break
        assert code == 202
        assert resp["seconds"] == 0.2
        time.sleep(0.5)  # let the clamped window close before teardown


# ---------------------------------------------------------------------------
# Tentpole 5 / satellite 6: the bench regression sentinel.
# ---------------------------------------------------------------------------

sys.path.insert(0, str(REPO / "scripts"))
import bench_gate  # noqa: E402


class TestBenchGate:
    def test_selftest_is_the_tier1_contract(self):
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "bench_gate.py"),
             "--selftest"],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "all legs passed" in proc.stdout

    def test_compare_directions_and_floor(self):
        committed = {"platform": "cpu", "rps": 100.0, "p95_ms": 10.0,
                     "overhead": {"ratio": 0.99,
                                  "with_obs": {"rps": 900.0}}}
        clean = bench_gate.compare(committed, json.loads(
            json.dumps(committed)), bench_gate.SPECS["BENCH_OBS.json"])
        assert not clean["violations"]
        bad = {"platform": "cpu", "rps": 50.0, "p95_ms": 30.0,
               "overhead": {"ratio": 0.80, "with_obs": {"rps": 900.0}}}
        verdict = bench_gate.compare(committed, bad,
                                     bench_gate.SPECS["BENCH_OBS.json"])
        flat = "\n".join(verdict["violations"])
        assert "rps" in flat and "p95_ms" in flat
        assert "overhead.ratio" in flat and "floor" in flat

    def test_committed_bench_obs_passes_its_own_specs(self):
        committed = json.loads((REPO / "BENCH_OBS.json").read_text())
        verdict = bench_gate.compare(committed, committed,
                                     bench_gate.SPECS["BENCH_OBS.json"])
        assert not verdict["violations"]
        assert verdict["checked"] > 2


# ---------------------------------------------------------------------------
# Acceptance pin: eegtpu-top --json over a LIVE 3-replica fleet plus a
# cells-shaped journal nest, cross-checked against /healthz + /metrics.
# ---------------------------------------------------------------------------

class TestOpsConsoleIntegration:
    def _write_cells_nest(self, root: Path) -> Path:
        """A synthetic cells-topology journal tree: front -> c0_obs ->
        cell run -> replica_obs -> replica run (THREE levels below the
        root — the nesting the old fixed-depth scan missed)."""
        now = time.time()
        front = root / "cells-front-run"
        deep = front / "c0_obs" / "cell-run" / "replica_obs" / "cell-rep"
        front.mkdir(parents=True)
        deep.mkdir(parents=True)
        with open(front / "events.jsonl", "w") as fh:
            fh.write(json.dumps({"event": "run_start", "t": now,
                                 "run_id": "cells-front"}) + "\n")
            fh.write(json.dumps({"event": "cell_front_start",
                                 "t": now}) + "\n")
            fh.write(json.dumps({"event": "cell_member", "t": now,
                                 "cell": "c0", "state": "live"}) + "\n")
        with open(deep / "events.jsonl", "w") as fh:
            fh.write(json.dumps({"event": "run_start", "t": now,
                                 "run_id": "cell-rep"}) + "\n")
            for _ in range(4):
                fh.write(json.dumps({"event": "request", "t": now,
                                     "status": "ok",
                                     "latency_ms": 2.0}) + "\n")
        return deep

    def test_top_json_matches_healthz_over_live_fleet(self, tmp_path,
                                                      capsys):
        from eegnetreplication_tpu.serve import service as serve_service
        from eegnetreplication_tpu.serve.fleet import (
            membership as fleet_ms,
        )

        root = tmp_path / "obsroot"
        root.mkdir()
        ck = _checkpoint(tmp_path)
        deep = self._write_cells_nest(root)
        sent = {}
        with ExitStack() as stack:
            front_jr = stack.enter_context(
                obs_journal.run(root, config={}, run_id="fleet-front"))
            front_jr.event("fleet_start", replicas=3, checkpoint=str(ck))
            apps, journal_dirs = [], []
            for i in range(3):
                jr = stack.enter_context(obs_journal.run(
                    front_jr.dir / "replica_obs", config={},
                    run_id=f"replica-{i}"))
                app = serve_service.ServeApp(
                    ck, port=0, buckets=(1, 4), max_wait_ms=1.0,
                    journal=jr).start()
                stack.callback(app.stop)
                apps.append(app)
                journal_dirs.append(jr.dir)
            replicas = [fleet_ms.Replica(f"r{i}", app.url,
                                         journal=front_jr)
                        for i, app in enumerate(apps)]
            membership = fleet_ms.FleetMembership(replicas, poll_s=0.1,
                                                  journal=front_jr)
            membership.start()
            stack.callback(membership.close)
            membership.wait_live(3, timeout_s=60.0)

            rng = np.random.RandomState(0)
            for i, app in enumerate(apps):
                sent[i] = i + 2
                for _ in range(sent[i]):
                    x = rng.randn(1, C, T).astype(np.float32)
                    code, _ = _post_json(app.url + "/predict",
                                         {"trials": x.tolist()})
                    assert code == 200

            # The console reads the SAME tree while everything is live.
            assert obs_top.main(["--json", str(root),
                                 "--window", "300"]) == 0
            snap = json.loads(capsys.readouterr().out.strip()
                              .splitlines()[-1])

            # Fleet membership (front journal) vs each replica's own
            # /healthz: both must call the same replicas live.
            for i, app in enumerate(apps):
                health = _get_json(app.url + "/healthz")
                assert health["status"] == "ok"
                assert snap["members"][f"r{i}"] == {"kind": "replica",
                                                    "state": "live"}
            # Plus the synthetic cells member: 4 runs' membership merged.
            assert snap["members"]["c0"] == {"kind": "cell",
                                             "state": "live"}
            assert snap["n_members"] == 4
            assert not snap["slo_breached"]
            assert snap["dropped_lines"] == 0

            by_dir = {r["dir"]: r for r in snap["runs"]}
            # The fleet rps header is the sum over EVERY run's window
            # rate (replicas + the synthetic cells replica).
            assert snap["rps"] == pytest.approx(
                sum(r["rps"] for r in snap["runs"]), abs=0.01)
            for i, app in enumerate(apps):
                view = by_dir[str(journal_dirs[i])]
                assert view["role"] == "serve"
                assert view["status"] == "live"
                assert view["run_id"] == f"replica-{i}"
                # Request accounting: the aggregator's fold must equal
                # the replica's own /metrics counters exactly.
                metrics = _get_json(app.url + "/metrics")
                served = sum(c["value"] for c in
                             metrics["counters"]["requests_total"])
                assert view["total_requests"] == sent[i] == served
                assert view["window_non_ok"] == 0
                assert view["rps"] > 0
                assert view["p95_ms"] >= view["p50_ms"] > 0

            # The three-level cells replica was discovered and folded.
            cell_view = by_dir[str(deep)]
            assert cell_view["total_requests"] == 4
            front_view = by_dir[str(front_jr.dir)]
            assert front_view["role"] == "fleet"
            assert front_view["members"].keys() == {"r0", "r1", "r2"}

            # The rendered frame (the --once path) carries the same rows.
            frame = obs_top.render(snap)
            assert "replica-0" in frame and "fleet-front" in frame
            assert "replica r0: live" in frame
