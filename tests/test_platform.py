"""Platform-selection utilities (offline-safe parts).

The probe itself needs a subprocess + possibly a live accelerator, so these
tests cover the pure-config pieces: the persistent compilation cache wiring
and the EEGTPU_PLATFORM override plumbing.
"""

import os
from unittest import mock

import jax

from eegnetreplication_tpu.utils.platform import enable_compilation_cache


def _restore_cache_config():
    return (
        jax.config.jax_compilation_cache_dir,
        jax.config.jax_persistent_cache_min_compile_time_secs,
        jax.config.jax_persistent_cache_min_entry_size_bytes,
    )


def _set_cache_config(saved):
    jax.config.update("jax_compilation_cache_dir", saved[0])
    jax.config.update("jax_persistent_cache_min_compile_time_secs", saved[1])
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", saved[2])


def test_enable_compilation_cache_sets_config(tmp_path):
    saved = _restore_cache_config()
    try:
        target = str(tmp_path / "xla_cache")
        with mock.patch.dict(os.environ,
                             {"EEGTPU_COMPILE_CACHE": target}):
            assert enable_compilation_cache() == target
        assert jax.config.jax_compilation_cache_dir == target
        # Thresholds lowered so the tiny-but-tunnel-expensive programs cache.
        assert jax.config.jax_persistent_cache_min_compile_time_secs <= 1.0
        assert jax.config.jax_persistent_cache_min_entry_size_bytes == 0
    finally:
        _set_cache_config(saved)


def test_enable_compilation_cache_disabled(tmp_path):
    saved = _restore_cache_config()
    try:
        for off in ("0", "false", "off"):
            with mock.patch.dict(os.environ, {"EEGTPU_COMPILE_CACHE": off}):
                assert enable_compilation_cache() is None
    finally:
        _set_cache_config(saved)


def test_enable_compilation_cache_truthy_means_default_path():
    """'=1' must enable the default path, not create a cwd dir named '1'."""
    saved = _restore_cache_config()
    try:
        for on in ("1", "true"):
            with mock.patch.dict(os.environ, {"EEGTPU_COMPILE_CACHE": on}):
                path = enable_compilation_cache()
            assert path is not None
            assert path.startswith("/tmp/eegtpu_xla_cache.")
        assert not os.path.exists("1")
    finally:
        _set_cache_config(saved)


def test_enable_compilation_cache_default_is_per_user():
    saved = _restore_cache_config()
    try:
        with mock.patch.dict(os.environ, clear=False) as env:
            env.pop("EEGTPU_COMPILE_CACHE", None)
            path = enable_compilation_cache()
        assert path is not None and path.startswith("/tmp/eegtpu_xla_cache.")
    finally:
        _set_cache_config(saved)


class TestProbe:
    """The accelerator probe must detect a stalled compiler and version its
    cache (the init-only probe's cached verdicts must never satisfy it)."""

    def test_hung_probe_times_out_and_caches_none(self, tmp_path):
        import time

        from eegnetreplication_tpu.utils import platform as plat

        with mock.patch.object(plat, "_PROBE_SRC",
                               "import time; time.sleep(600)"), \
             mock.patch.object(plat, "_probe_cache_path",
                               lambda: str(tmp_path / "probe.json")), \
             mock.patch.dict(os.environ, {"EEGTPU_PROBE_CACHE": "1"}):
            t0 = time.perf_counter()
            assert plat.probe_accelerator(timeout_s=2.0) is None
            assert time.perf_counter() - t0 < 30  # killed, not waited out
            assert (tmp_path / "probe.json").exists()
            # The hung outcome must be served from the cache: a re-probe
            # spawning another subprocess would mean the cache regressed.
            with mock.patch.object(
                    plat.subprocess, "Popen",
                    side_effect=AssertionError("cache miss re-spawned")):
                assert plat.probe_accelerator(timeout_s=2.0) is None

    def test_failing_probe_returns_none(self, tmp_path):
        from eegnetreplication_tpu.utils import platform as plat

        with mock.patch.object(plat, "_PROBE_SRC", "raise SystemExit(3)"), \
             mock.patch.object(plat, "_probe_cache_path",
                               lambda: str(tmp_path / "probe.json")):
            assert plat.probe_accelerator(timeout_s=30.0) is None

    def test_cache_key_versions_probe_source(self, tmp_path):
        """A cache entry from a different probe program must be a miss."""
        import json
        import time

        from eegnetreplication_tpu.utils import platform as plat

        path = tmp_path / "probe.json"
        with mock.patch.object(plat, "_probe_cache_path", lambda: str(path)), \
             mock.patch.dict(os.environ, {"EEGTPU_PROBE_CACHE": "1"}):
            old_key = plat._probe_env_key()
            with mock.patch.object(plat, "_PROBE_SRC", "pass"):
                assert plat._probe_env_key() != old_key
                # entry written under the real probe's key: miss for "pass"
                path.write_text(json.dumps(
                    {"ts": time.time(), "result": "tpu", "env": old_key}))
                assert plat._read_probe_cache() is plat._MISS
            # and under its own key: hit
            path.write_text(json.dumps(
                {"ts": time.time(), "result": "tpu",
                 "env": plat._probe_env_key()}))
            assert plat._read_probe_cache() == "tpu"


class TestSelectPlatformInfo:
    """Retry + diagnostics semantics of the shared selection helper."""

    @staticmethod
    def _clear_forced():
        """Drop any ambient EEGTPU_PLATFORM: the forced-override path would
        short-circuit before the mocked probe (this project's CPU dress
        runs export it routinely)."""
        env = {k: v for k, v in os.environ.items()
               if k != "EEGTPU_PLATFORM"}
        return mock.patch.dict(os.environ, env, clear=True)

    def _patch_probe(self, outcomes):
        from eegnetreplication_tpu.utils import platform as plat

        calls = []

        def fake(timeout_s=90.0, refresh=False):
            calls.append({"refresh": refresh})
            result, reason = outcomes[min(len(calls) - 1,
                                          len(outcomes) - 1)]
            return {"result": result, "reason": reason, "seconds": 0.1,
                    "cached": False}

        return mock.patch.object(plat, "probe_accelerator_info", fake), calls

    def test_retry_recovers_and_bypasses_cache_read(self):
        from eegnetreplication_tpu.utils import platform as plat

        patcher, calls = self._patch_probe(
            [(None, "probe timed out after 90s"), ("axon", "ok")])
        with patcher, self._clear_forced(), \
             mock.patch.object(plat, "enable_compilation_cache",
                               lambda: "/tmp/cache"):
            name, info = plat.select_platform_info(retries=2,
                                                   retry_sleep_s=0.0)
        assert name == "axon"
        assert info["attempts"] == 2
        assert info["fallback_reason"] is None
        assert info["cache_dir"] == "/tmp/cache"
        # attempt 0 may use the cache; retries must refresh
        assert [c["refresh"] for c in calls] == [False, True]

    def test_exhausted_retries_fall_back_with_reasons(self):
        from eegnetreplication_tpu.utils import platform as plat

        patcher, calls = self._patch_probe(
            [(None, "probe timed out after 90s")])
        with patcher, self._clear_forced(), \
             mock.patch.object(plat, "force_cpu", lambda: True):
            name, info = plat.select_platform_info(retries=1,
                                                   retry_sleep_s=0.0)
        assert name == "cpu"
        assert info["attempts"] == 2
        assert "probe timed out" in info["fallback_reason"]

    def test_spawn_failure_short_circuits_retries(self):
        from eegnetreplication_tpu.utils import platform as plat

        patcher, calls = self._patch_probe(
            [(None, "probe spawn failed: boom")])
        with patcher, self._clear_forced(), \
             mock.patch.object(plat, "force_cpu", lambda: True):
            name, info = plat.select_platform_info(retries=3,
                                                   retry_sleep_s=0.0)
        assert name == "cpu"
        assert info["attempts"] == 1  # no pointless retries

    def test_forced_platform_skips_probe(self):
        from eegnetreplication_tpu.utils import platform as plat

        with mock.patch.dict(os.environ, {"EEGTPU_PLATFORM": "cpu"}), \
             mock.patch.object(plat, "probe_accelerator_info",
                               side_effect=AssertionError("probed anyway")):
            name, info = plat.select_platform_info()
        assert name == "cpu" and info["forced"] is True
