"""Platform-selection utilities (offline-safe parts).

The probe itself needs a subprocess + possibly a live accelerator, so these
tests cover the pure-config pieces: the persistent compilation cache wiring
and the EEGTPU_PLATFORM override plumbing.
"""

import os
from unittest import mock

import jax

from eegnetreplication_tpu.utils.platform import enable_compilation_cache


def _restore_cache_config():
    return (
        jax.config.jax_compilation_cache_dir,
        jax.config.jax_persistent_cache_min_compile_time_secs,
        jax.config.jax_persistent_cache_min_entry_size_bytes,
    )


def _set_cache_config(saved):
    jax.config.update("jax_compilation_cache_dir", saved[0])
    jax.config.update("jax_persistent_cache_min_compile_time_secs", saved[1])
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", saved[2])


def test_enable_compilation_cache_sets_config(tmp_path):
    saved = _restore_cache_config()
    try:
        target = str(tmp_path / "xla_cache")
        with mock.patch.dict(os.environ,
                             {"EEGTPU_COMPILE_CACHE": target}):
            assert enable_compilation_cache() == target
        assert jax.config.jax_compilation_cache_dir == target
        # Thresholds lowered so the tiny-but-tunnel-expensive programs cache.
        assert jax.config.jax_persistent_cache_min_compile_time_secs <= 1.0
        assert jax.config.jax_persistent_cache_min_entry_size_bytes == 0
    finally:
        _set_cache_config(saved)


def test_enable_compilation_cache_disabled(tmp_path):
    saved = _restore_cache_config()
    try:
        for off in ("0", "false", "off"):
            with mock.patch.dict(os.environ, {"EEGTPU_COMPILE_CACHE": off}):
                assert enable_compilation_cache() is None
    finally:
        _set_cache_config(saved)


def test_enable_compilation_cache_truthy_means_default_path():
    """'=1' must enable the default path, not create a cwd dir named '1'."""
    saved = _restore_cache_config()
    try:
        for on in ("1", "true"):
            with mock.patch.dict(os.environ, {"EEGTPU_COMPILE_CACHE": on}):
                path = enable_compilation_cache()
            assert path is not None
            assert path.startswith("/tmp/eegtpu_xla_cache.")
        assert not os.path.exists("1")
    finally:
        _set_cache_config(saved)


def test_enable_compilation_cache_default_is_per_user():
    saved = _restore_cache_config()
    try:
        with mock.patch.dict(os.environ, clear=False) as env:
            env.pop("EEGTPU_COMPILE_CACHE", None)
            path = enable_compilation_cache()
        assert path is not None and path.startswith("/tmp/eegtpu_xla_cache.")
    finally:
        _set_cache_config(saved)
