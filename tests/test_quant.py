"""int8 weight quantization (``eegnetreplication_tpu/ops/quant.py``).

Covers the ISSUE-8 tentpole surface: per-channel symmetric quantize ->
dequantize round-trip error bounds per layer, the flat npz round trip
preserving the ``resil/integrity`` digest contract, the specialized
quantized EEGNet forward's argmax agreement with fp32, and the generic
dequantize-then-apply fallback for models the specialization does not
encode.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from eegnetreplication_tpu.models import EEGNet  # noqa: E402
from eegnetreplication_tpu.ops import quant  # noqa: E402
from eegnetreplication_tpu.resil.integrity import IntegrityError  # noqa: E402
from eegnetreplication_tpu.training.steps import eval_forward  # noqa: E402

C, T = 4, 64


def _variables(seed: int = 0, **model_kw):
    model = EEGNet(n_channels=C, n_times=T, **model_kw)
    variables = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, C, T)),
                           train=False)
    return model, variables["params"], variables["batch_stats"]


@pytest.fixture(scope="module")
def stock():
    return _variables()


class TestQuantizeRoundTrip:
    def test_per_channel_scales_and_int8_range(self, stock):
        _, params, _ = stock
        qparams = quant.quantize_params(params)
        for layer in ("temporal_conv", "spatial_conv",
                      "separable_depthwise", "separable_pointwise",
                      "classifier"):
            leaf = qparams[layer]["kernel"]
            assert quant.is_qleaf(leaf)
            w = np.asarray(params[layer]["kernel"])
            assert leaf["q"].dtype == np.int8
            assert np.abs(leaf["q"]).max() <= quant.QMAX
            # One scale per OUTPUT channel (last axis), broadcast shape.
            assert leaf["scale"].shape[-1] == w.shape[-1]
            assert leaf["scale"].size == w.shape[-1]

    def test_bn_and_bias_stay_fp32(self, stock):
        _, params, _ = stock
        qparams = quant.quantize_params(params)
        assert not quant.is_qleaf(qparams["temporal_bn"]["scale"])
        assert qparams["classifier"]["bias"].dtype == np.float32
        assert np.array_equal(qparams["classifier"]["bias"],
                              np.asarray(params["classifier"]["bias"]))

    def test_round_trip_error_bounded_per_layer(self, stock):
        """ISSUE-8 satellite: the quantize->dequantize error per layer is
        bounded by scale/2 elementwise (symmetric round-to-nearest)."""
        _, params, _ = stock
        qparams = quant.quantize_params(params)
        errs = quant.quantization_error(params, qparams)
        assert set(errs) == {
            "temporal_conv/kernel", "spatial_conv/kernel",
            "separable_depthwise/kernel", "separable_pointwise/kernel",
            "classifier/kernel"}
        for layer, rec in errs.items():
            assert rec["max_abs_err"] <= rec["bound"] + 1e-7, layer
            assert rec["rel_fro"] < 0.01, layer  # <1% Frobenius drift

    def test_dequantize_restores_structure(self, stock):
        _, params, _ = stock
        restored = quant.dequantize_params(quant.quantize_params(params))
        flat_p = jax.tree_util.tree_leaves_with_path(dict(params))
        flat_r = jax.tree_util.tree_leaves_with_path(restored)
        assert len(flat_p) == len(flat_r)
        for (path_p, leaf_p), (path_r, leaf_r) in zip(flat_p, flat_r):
            assert path_p == path_r
            assert leaf_p.shape == leaf_r.shape

    def test_all_zero_channel_keeps_unit_scale(self):
        w = np.zeros((3, 5), np.float32)
        w[:, 0] = [1.0, -2.0, 0.5]
        leaf = quant.quantize_tensor(w)
        assert np.all(leaf["scale"][:, 1:] == 1.0)
        assert np.all(leaf["q"][:, 1:] == 0)
        np.testing.assert_allclose(
            np.asarray(quant.dequantize_tensor(leaf))[:, 0], w[:, 0],
            atol=float(leaf["scale"][0, 0]) / 2 + 1e-7)


class TestFlatRoundTrip:
    def test_flatten_unflatten_identity(self, stock):
        _, params, _ = stock
        qparams = quant.quantize_params(params)
        back = quant.unflatten_qparams(quant.flatten_qparams(qparams))

        def assert_equal(a, b, path=()):
            if quant.is_qleaf(a):
                assert quant.is_qleaf(b), path
                np.testing.assert_array_equal(a["q"], b["q"])
                np.testing.assert_array_equal(a["scale"], b["scale"])
                return
            if hasattr(a, "items"):
                assert set(a) == set(b), path
                for k in a:
                    assert_equal(a[k], b[k], path + (k,))
                return
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        assert_equal(qparams, back)

    def test_digest_survives_npz_round_trip(self, stock, tmp_path):
        """ISSUE-8 tentpole clause: the quantized pytree's content digest
        (resil/integrity contract) is identical across save->load."""
        _, params, _ = stock
        qparams = quant.quantize_params(params)
        digest = quant.qparams_digest(qparams)
        path = quant.save_quantized(tmp_path / "q.npz", qparams,
                                    metadata={"n_channels": C})
        loaded, metadata = quant.load_quantized(path)
        assert metadata == {"n_channels": C}
        assert quant.qparams_digest(loaded) == digest

    def test_content_tamper_raises_integrity_error(self, stock, tmp_path):
        _, params, _ = stock
        path = quant.save_quantized(tmp_path / "q.npz",
                                    quant.quantize_params(params))
        with np.load(path) as data:
            flat = {k: np.array(data[k]) for k in data.files}
        # Flip one quantized weight; keep the stale digest entry.
        key = next(k for k in flat if k.endswith(".q"))
        flat[key] = flat[key].copy()
        flat[key].flat[0] = flat[key].flat[0] ^ 0x7F
        with open(path, "wb") as fh:
            np.savez(fh, **flat)
        with pytest.raises(IntegrityError):
            quant.load_quantized(path)

    def test_quantization_is_deterministic(self, stock):
        _, params, _ = stock
        assert quant.qparams_digest(quant.quantize_params(params)) \
            == quant.qparams_digest(quant.quantize_params(params))


class TestQuantizedForward:
    def test_specialized_forward_argmax_matches_fp32(self, stock):
        model, params, batch_stats = stock
        assert quant.supports_quantized_eval(model)
        qparams = quant.quantize_params(params)
        x = jnp.asarray(np.random.RandomState(3).randn(
            256, C, T).astype(np.float32))
        ref = np.argmax(np.asarray(
            eval_forward(model, params, batch_stats, x)), axis=-1)
        got = np.argmax(np.asarray(jax.jit(
            lambda xx: quant.quantized_eval_forward(
                model, qparams, batch_stats, xx))(x)), axis=-1)
        agreement = float(np.mean(ref == got))
        # The serving gate's floor; random-init weights are the worst
        # case (trained checkpoints measure 1.0).
        assert agreement >= 0.99

    def test_generic_fallback_matches_dequantized_eval(self):
        """A model the specialization does not encode (non-HIGHEST
        precision EEGNet) serves int8 via dequantize-then-apply, exactly
        equal to the regular eval forward on the dequantized weights."""
        model, params, batch_stats = _variables(precision=None)
        assert not quant.supports_quantized_eval(model)
        qparams = quant.quantize_params(params)
        x = jnp.asarray(np.random.RandomState(4).randn(
            8, C, T).astype(np.float32))
        got = np.asarray(quant.quantized_eval_forward(
            model, qparams, batch_stats, x))
        want = np.asarray(eval_forward(
            model, quant.dequantize_params(qparams), batch_stats, x,
            allow_pallas=False))
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_wide_variant_supported(self):
        """The specialization is generic over (F1, D): eegnet_wide's
        grouping and flatten order agree with the stock forward."""
        model = EEGNet(n_channels=C, n_times=T, F1=4, D=4)
        variables = model.init(jax.random.PRNGKey(1),
                               jnp.zeros((1, C, T)), train=False)
        params, batch_stats = variables["params"], variables["batch_stats"]
        qparams = quant.quantize_params(params)
        x = jnp.asarray(np.random.RandomState(5).randn(
            64, C, T).astype(np.float32))
        ref = np.argmax(np.asarray(
            eval_forward(model, params, batch_stats, x)), axis=-1)
        got = np.argmax(np.asarray(quant.quantized_eval_forward(
            model, qparams, batch_stats, x)), axis=-1)
        assert float(np.mean(ref == got)) >= 0.99
