"""Telemetry subsystem tests: schema, metrics registry, run journal, and
the protocols' on-chip instrumentation end-to-end (all CPU).

The journal/metrics/schema trio replaces three ad-hoc measurement paths;
these tests pin the contracts that make that worthwhile: every emitted
event validates (no ``_schema_error`` ever appears), a protocol run under
a run context yields a complete ``events.jsonl`` + ``metrics.json``, a
device fault is journaled with its retry wall, and ``scripts/obs_report.py``
renders what the journal wrote.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from eegnetreplication_tpu import obs
from eegnetreplication_tpu.config import DEFAULT_TRAINING, Paths
from eegnetreplication_tpu.obs import MetricsRegistry, schema
from synthetic import make_loader

REPO = Path(__file__).resolve().parents[1]
CFG = DEFAULT_TRAINING.replace(batch_size=16)


def tiny_loader():
    return make_loader(n_trials=24, n_channels=4, n_times=32, class_sep=1.5)


class TestSchema:
    def test_event_missing_required_keys_raises(self):
        with pytest.raises(schema.SchemaError, match="missing required"):
            schema.validate_event({"event": "epoch", "t": 1.0,
                                   "run_id": "r", "epoch": 1})

    def test_unknown_event_type_allowed_with_base_keys(self):
        schema.validate_event({"event": "custom_probe", "t": 1.0,
                               "run_id": "r", "anything": True})

    def test_complete_stream_needs_start_and_end(self):
        ep = {"event": "epoch", "t": 1.0, "run_id": "r", "epoch": 1,
              "total_epochs": 1, "train_loss": 1.0, "val_loss": 1.0,
              "val_acc": 50.0, "grad_norm": 0.5, "n_folds": 4}
        with pytest.raises(schema.SchemaError, match="run_start"):
            schema.validate_events([ep])
        # but a live/partial stream is fine with complete=False
        schema.validate_events([ep], complete=False)

    def test_metrics_validation(self):
        good = MetricsRegistry()
        good.inc("n", 2.0)
        schema.validate_metrics(good.snapshot("rid"))
        with pytest.raises(schema.SchemaError):
            schema.validate_metrics({"schema_version": 1, "run_id": "r",
                                     "utc": "t", "counters": {},
                                     "gauges": {}})  # histograms missing

    def test_every_declared_event_type_round_trips(self, tmp_path):
        """One synthetic event of EVERY type in EVENT_REQUIRED survives
        validate/read_events/event_summary.

        This is the drift guard for the declaration side: a newly added
        event type whose required-key tuple is malformed (or whose keys
        the validator cannot satisfy) fails here loudly, the moment it
        is declared — not when the first real run emits it.
        """
        def ev(kind):
            e = {"event": kind, "t": 1.0, "run_id": "r1"}
            for key in schema.EVENT_REQUIRED[kind]:
                assert isinstance(key, str), \
                    f"{kind!r} declares a non-str required key {key!r}"
                e[key] = 1
            return schema.validate_event(e)

        middle = [k for k in schema.EVENT_REQUIRED
                  if k not in ("run_start", "run_end")]
        events = [ev("run_start")] + [ev(k) for k in middle] \
            + [ev("run_end")]
        path = tmp_path / "events.jsonl"
        path.write_text("".join(json.dumps(e) + "\n" for e in events))
        loaded = schema.read_events(path)
        assert [e["event"] for e in loaded] == [e["event"] for e in events]
        summary = schema.event_summary(loaded)
        assert summary["n_events"] == len(events)
        # A declared type with its required key stripped must fail: the
        # loud-failure guarantee a new declaration buys.
        for kind in middle:
            if not schema.EVENT_REQUIRED[kind]:
                continue
            bad = dict(ev(kind))
            bad.pop(schema.EVENT_REQUIRED[kind][0])
            with pytest.raises(schema.SchemaError):
                schema.validate_event(bad)

    def test_bench_writer_stamps_and_validates(self, tmp_path):
        path = tmp_path / "BENCH_X.json"
        schema.write_json_artifact(path, {"platform": "cpu", "value": 1.5})
        rec = json.loads(path.read_text())
        assert rec["schema_version"] == schema.SCHEMA_VERSION
        assert "utc" in rec and rec["value"] == 1.5
        schema.validate_bench(rec)
        with pytest.raises(schema.SchemaError, match="platform"):
            schema.write_json_artifact(tmp_path / "bad.json", {"value": 2})


class TestMetricsRegistry:
    def test_counter_accumulates_per_label_series(self):
        m = MetricsRegistry()
        m.inc("fold_epochs_total", 10)
        m.inc("fold_epochs_total", 26)
        m.inc("fold_epochs_total", 5, group="1")
        assert m.get("fold_epochs_total") == 36
        assert m.get("fold_epochs_total", group="1") == 5
        with pytest.raises(ValueError, match="cannot decrease"):
            m.inc("fold_epochs_total", -1)

    def test_gauge_last_write_wins(self):
        m = MetricsRegistry()
        m.set("hbm_bytes_in_use", 100, device="0")
        m.set("hbm_bytes_in_use", 200, device="0")
        m.set("hbm_bytes_in_use", 50, device="1")
        assert m.get("hbm_bytes_in_use", device="0") == 200
        assert m.get("hbm_bytes_in_use", device="1") == 50

    def test_histogram_aggregation(self):
        m = MetricsRegistry()
        for v in (1.0, 3.0, 2.0):
            m.observe("chunk_wall_s", v)
        snap = m.snapshot("rid")
        [h] = snap["histograms"]["chunk_wall_s"]
        assert h["count"] == 3 and h["sum"] == 6.0
        assert h["min"] == 1.0 and h["max"] == 3.0 and h["mean"] == 2.0

    def test_kind_collision_rejected(self):
        m = MetricsRegistry()
        m.inc("x")
        with pytest.raises(ValueError, match="different kind"):
            m.set("x", 1.0)

    def test_flush_roundtrip(self, tmp_path):
        m = MetricsRegistry()
        m.inc("a", 2)
        m.set("b", 3.5)
        m.observe("c", 0.25)
        path = m.flush(tmp_path / "metrics.json", run_id="rid")
        rec = schema.read_metrics(path)
        assert rec["run_id"] == "rid"
        assert rec["counters"]["a"][0]["value"] == 2


class TestRunJournal:
    def test_run_context_roundtrip(self, tmp_path):
        with obs.run(tmp_path, config={"epochs": 2}, note="test") as jr:
            assert obs.current() is jr
            jr.event("compile_begin", what="x")
            jr.event("compile_end", what="x", elapsed_s=0.5)
            jr.metrics.inc("fold_epochs_total", 8)
        events = schema.read_events(jr.events_path)
        kinds = [e["event"] for e in events]
        assert kinds[0] == "run_start" and kinds[-1] == "run_end"
        assert events[0]["config"] == {"epochs": 2}
        assert events[0]["device_kind"]
        assert events[-1]["status"] == "ok"
        assert not any("_schema_error" in e for e in events)
        metrics = schema.read_metrics(jr.metrics_path)
        assert metrics["counters"]["fold_epochs_total"][0]["value"] == 8
        assert metrics["gauges"]["wall_seconds"][0]["value"] >= 0

    def test_exception_journals_error_status(self, tmp_path):
        with pytest.raises(RuntimeError, match="boom"):
            with obs.run(tmp_path) as jr:
                raise RuntimeError("boom")
        events = schema.read_events(jr.events_path)
        assert events[-1]["status"] == "error"
        assert "boom" in events[-1]["error"]

    def test_no_context_is_inert(self):
        jr = obs.current()
        assert not jr.active
        jr.event("epoch")  # must not raise or write anywhere
        jr.metrics.inc("x")
        jr.run_end()

    def test_dataclass_config_with_nested_path_serializes(self, tmp_path):
        import dataclasses

        @dataclasses.dataclass
        class Cfg:
            out: Path
            arr: object

        with obs.run(tmp_path,
                     config=Cfg(out=tmp_path / "x", arr=np.arange(3))) as jr:
            pass
        events = schema.read_events(jr.events_path)
        cfg = events[0]["config"]
        assert cfg["out"] == str(tmp_path / "x")
        assert isinstance(cfg["arr"], str)  # repr-coerced, not a crash

    def test_unserializable_event_field_does_not_raise(self, tmp_path):
        with obs.run(tmp_path) as jr:
            jr.event("custom_probe", blob={1, 2})  # a set: not JSON
        events = schema.read_events(jr.events_path)
        probe = next(e for e in events if e["event"] == "custom_probe")
        assert isinstance(probe["blob"], str)

    def test_invalid_event_is_flagged_not_fatal(self, tmp_path):
        with obs.run(tmp_path) as jr:
            jr.event("epoch", epoch=1)  # missing most required keys
        events = schema.read_events(jr.events_path)
        bad = [e for e in events if e["event"] == "epoch"]
        assert bad and "_schema_error" in bad[0]


class TestProtocolTelemetry:
    def _run_ws(self, tmp_path, **kw):
        from eegnetreplication_tpu.training.protocols import (
            within_subject_training,
        )

        with obs.run(tmp_path / "obs", config=CFG) as jr:
            result = within_subject_training(
                epochs=3, config=CFG, loader=tiny_loader(), subjects=(1,),
                paths=Paths.from_root(tmp_path), seed=0, save_models=False,
                **kw)
        return result, jr

    def test_ws_smoke_writes_complete_journal(self, tmp_path):
        result, jr = self._run_ws(tmp_path, checkpoint_every=2)
        events = schema.read_events(jr.events_path)
        kinds = [e["event"] for e in events]
        assert kinds[0] == "run_start" and kinds[-1] == "run_end"
        assert "train_setup" in kinds and "compile_end" in kinds
        assert not any("_schema_error" in e for e in events)
        setup = next(e for e in events if e["event"] == "train_setup")
        assert setup["n_folds"] == 4 and setup["epochs"] == 3
        assert setup["real_train_samples"] > 0
        epochs = [e for e in events if e["event"] == "epoch"]
        assert len(epochs) == 3  # chunked path journals every epoch live
        for ev in epochs:
            assert np.isfinite(ev["train_loss"])
            assert np.isfinite(ev["val_loss"])
            assert ev["grad_norm"] > 0  # real gradients flowed
        metrics = schema.read_metrics(jr.metrics_path)
        assert metrics["counters"]["fold_epochs_total"][0]["value"] == 12
        assert metrics["histograms"]["chunk_wall_s"][0]["count"] == 2

    def test_ws_single_program_journals_epochs_posthoc(self, tmp_path):
        result, jr = self._run_ws(tmp_path)  # 3 epochs -> one fused program
        events = schema.read_events(jr.events_path)
        epochs = [e for e in events if e["event"] == "epoch"]
        assert len(epochs) == 3
        assert all(e["grad_norm"] > 0 for e in epochs)
        compile_end = next(e for e in events if e["event"] == "compile_end")
        assert compile_end["includes_execution"] is True

    def test_device_fault_journaled_with_retry_wall(self, tmp_path,
                                                    monkeypatch):
        from eegnetreplication_tpu.training import protocols as P

        monkeypatch.setattr(P, "_fold_batch_limit_path",
                            lambda: tmp_path / "limits.json")
        # 4 folds at fold_batch=3: group 0 (3 folds) exceeds the injected
        # 2-fold device limit, faults, halves to 1, completes all folds.
        result, jr = self._run_ws(tmp_path, fold_batch=3,
                                  _fault_if_folds_over=2)
        events = schema.read_events(jr.events_path)
        faults = [e for e in events if e["event"] == "device_fault"]
        assert faults, "the injected fault must be journaled"
        assert faults[0]["retry_fold_batch"] == 1
        assert "UNAVAILABLE" in faults[0]["error"]
        metrics = schema.read_metrics(jr.metrics_path)
        assert metrics["counters"]["device_fault_retries"][0]["value"] >= 1
        # ADVICE r5: the faulted attempt's wall is accounted, both in the
        # metric and in the protocol's wall_seconds.
        assert metrics["counters"]["fault_retry_wall_s"][0]["value"] > 0
        assert result.fault_retry_wall_s > 0
        assert result.wall_seconds >= result.fault_retry_wall_s

    def test_obs_report_renders_run(self, tmp_path):
        _, jr = self._run_ws(tmp_path, checkpoint_every=2)
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "obs_report.py"),
             str(tmp_path / "obs")],
            capture_output=True, text=True, timeout=120,
            env=dict(os.environ, EEGTPU_NO_LOG_FILE="1"))
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert jr.run_id in proc.stdout
        assert "within_subject" in proc.stdout
        proc_json = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "obs_report.py"),
             "--json", str(jr.dir)],
            capture_output=True, text=True, timeout=120,
            env=dict(os.environ, EEGTPU_NO_LOG_FILE="1"))
        assert proc_json.returncode == 0, proc_json.stderr[-2000:]
        summary = json.loads(proc_json.stdout.strip().splitlines()[-1])
        assert summary["status"] == "ok"
        assert summary["n_epoch_events"] == 3
        assert summary["fold_epochs_total"] == 12


class TestHistogramBuckets:
    """PR 9: the registry's histograms carry fixed log-spaced buckets so
    live quantiles exist without journal scans."""

    def test_bucket_boundary_le_semantics(self):
        from eegnetreplication_tpu.obs.metrics import _Histogram

        h = _Histogram(bounds=(1.0, 10.0, 100.0))
        for v in (0.5, 1.0, 1.0001, 10.0, 99.0, 100.0, 100.0001):
            h.observe(v)
        # Prometheus le semantics: a bucket counts observations <= bound
        # (exact boundary values land in the bucket they bound).
        assert h.buckets == [2, 2, 2, 1]
        assert sum(h.buckets) == h.count == 7

    def test_quantile_within_one_bucket_width(self):
        from eegnetreplication_tpu.obs.metrics import (
            DEFAULT_BUCKET_BOUNDS,
            _Histogram,
        )
        from eegnetreplication_tpu.obs.stats import percentile

        rng = np.random.RandomState(0)
        values = (rng.lognormal(mean=2.0, sigma=1.0, size=5000)
                  .astype(float).tolist())
        h = _Histogram()
        for v in values:
            h.observe(v)
        bounds = list(DEFAULT_BUCKET_BOUNDS)
        for q in (0.5, 0.95, 0.99):
            exact = percentile(values, q)
            est = h.quantile(q)
            # Within one bucket width: the estimate and the exact order
            # statistic share a bucket or an adjacent boundary.
            import bisect

            i = bisect.bisect_left(bounds, exact)
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i] if i < len(bounds) else h.max
            assert lo * 0.999 <= est <= hi * 1.001, (q, exact, est, lo, hi)

    def test_empty_and_single_observation(self):
        from eegnetreplication_tpu.obs.metrics import _Histogram

        h = _Histogram()
        assert h.quantile(0.95) == 0.0
        h.observe(42.0)
        assert h.quantile(0.0) <= 42.0 <= h.max
        # The estimate is clamped to the observed range.
        assert h.quantile(0.99) <= 42.0 * 1.0001

    def test_registry_quantile_and_snapshot_roundtrip(self, tmp_path):
        reg = MetricsRegistry()
        for v in range(1, 101):
            reg.observe("latency_ms", float(v))
        p95 = reg.quantile("latency_ms", 0.95)
        assert p95 is not None and 80.0 <= p95 <= 100.0
        assert reg.quantile("nope", 0.5) is None
        snap = reg.snapshot()
        entry = snap["histograms"]["latency_ms"][0]
        assert sum(entry["buckets"]) == entry["count"] == 100
        assert len(entry["buckets"]) == len(entry["bounds"]) + 1
        # The flushed artifact still validates against the schema.
        schema.validate_metrics(snap)


class TestPrometheusExposition:
    def _snapshot(self):
        reg = MetricsRegistry()
        reg.inc("requests_total", 3, status="ok")
        reg.inc("requests_total", 1, status='we"ird\nlabel\\x')
        reg.set("queue_depth", 7.0)
        reg.observe("latency_ms", 2.0)
        reg.observe("latency_ms", 50.0)
        return reg.snapshot()

    def test_text_format_sections(self):
        from eegnetreplication_tpu.obs.metrics import to_prometheus_text

        text = to_prometheus_text(self._snapshot())
        assert "# TYPE requests_total counter" in text
        assert 'requests_total{status="ok"} 3' in text
        assert "# TYPE queue_depth gauge" in text
        assert "# TYPE latency_ms histogram" in text
        assert "latency_ms_count 2" in text
        assert "latency_ms_sum 52" in text
        assert 'latency_ms_bucket{le="+Inf"} 2' in text

    def test_label_escaping(self):
        from eegnetreplication_tpu.obs.metrics import to_prometheus_text

        text = to_prometheus_text(self._snapshot())
        # Backslash, double quote, and newline are escaped per the
        # exposition format; the raw forms must not appear.
        assert 'status="we\\"ird\\nlabel\\\\x"' in text
        assert "\nlabel" not in text.replace("\\n", "")

    def test_histogram_buckets_cumulative(self):
        from eegnetreplication_tpu.obs.metrics import to_prometheus_text

        text = to_prometheus_text(self._snapshot())
        counts = []
        for line in text.splitlines():
            if line.startswith("latency_ms_bucket"):
                counts.append(int(line.rsplit(" ", 1)[1]))
        assert counts == sorted(counts)  # cumulative, monotonically up
        assert counts[-1] == 2           # +Inf equals the count

    def test_content_negotiation_helper(self):
        from eegnetreplication_tpu.obs.metrics import wants_prometheus

        assert not wants_prometheus(None)
        assert not wants_prometheus("application/json")
        assert not wants_prometheus("*/*")
        assert wants_prometheus("text/plain; version=0.0.4")
        assert wants_prometheus(
            "application/openmetrics-text;version=1.0.0,text/plain")


class TestTrace:
    def test_span_nesting_and_parentage(self, tmp_path):
        from eegnetreplication_tpu.obs import trace

        with obs.run(tmp_path / "obs", config={}) as jr:
            ctx = trace.TraceContext(trace.new_trace_id(), sampled=True)
            with trace.use(ctx):
                with trace.span("outer", journal=jr) as outer:
                    with trace.span("inner", journal=jr) as inner:
                        pass
        events = schema.read_events(jr.events_path)
        spans = {e["name"]: e for e in events if e["event"] == "span"}
        assert spans["inner"]["parent_span_id"] == outer.span_id
        assert spans["outer"]["parent_span_id"] is None
        assert spans["inner"]["trace_id"] == spans["outer"]["trace_id"]
        assert spans["inner"]["span_id"] == inner.span_id
        # inner closed first: journal order is inner, outer.
        names = [e["name"] for e in events if e["event"] == "span"]
        assert names == ["inner", "outer"]
        assert not any("_schema_error" in e for e in events)

    def test_unsampled_buffers_and_anomaly_flush(self, tmp_path):
        from eegnetreplication_tpu.obs import trace

        with obs.run(tmp_path / "obs", config={}) as jr:
            ctx = trace.TraceContext(trace.new_trace_id(), sampled=False)
            with trace.use(ctx):
                with trace.span("buffered", journal=jr):
                    pass
                assert not [e for e in schema.read_events(
                    jr.events_path, complete=False)
                    if e["event"] == "span"]
                # A non-anomalous status flushes nothing...
                assert trace.flush_if_anomalous("ok", journal=jr) == 0
                # ...an anomalous one writes the buffer and latches the
                # trace so later spans journal directly.
                assert trace.flush_if_anomalous("error", journal=jr) == 1
                with trace.span("after_flush", journal=jr):
                    pass
        spans = [e for e in schema.read_events(jr.events_path)
                 if e["event"] == "span"]
        assert [s["name"] for s in spans] == ["buffered", "after_flush"]

    def test_header_roundtrip(self):
        from eegnetreplication_tpu.obs import trace

        ctx = trace.TraceContext(trace.new_trace_id(),
                                 span_id=trace.new_span_id(), sampled=True)
        headers = trace.headers(ctx)
        back = trace.from_headers(headers)
        assert back.trace_id == ctx.trace_id
        assert back.span_id == ctx.span_id
        assert back.sampled is True
        assert trace.from_headers({}) is None
        # maybe_start: propagated context wins; rate 0 disables tracing.
        assert trace.maybe_start(headers, 0.0).trace_id == ctx.trace_id
        assert trace.maybe_start({}, 0.0) is None
        assert trace.maybe_start({}, 1.0).sampled is True

    def test_sampling_rate_zero_and_one(self):
        from eegnetreplication_tpu.obs import trace

        assert not trace.start(0.0).sampled
        assert trace.start(1.0).sampled

    def test_stitch_cross_process_trees(self, tmp_path):
        """Two 'processes' (journals) sharing one trace id stitch into a
        single tree with the cross-process parent link intact."""
        from eegnetreplication_tpu.obs import trace

        trace_id = trace.new_trace_id()
        with obs.run(tmp_path / "router_obs", config={}) as rj:
            ctx = trace.TraceContext(trace_id, sampled=True)
            with trace.use(ctx):
                with trace.span("router.dispatch", journal=rj) as root:
                    pass
        with obs.run(tmp_path / "replica_obs", config={}) as pj:
            child = trace.TraceContext(trace_id, span_id=root.span_id,
                                       sampled=True)
            with trace.use(child):
                with trace.span("replica.request", journal=pj):
                    with trace.span("queue.wait", journal=pj):
                        pass
        trees = trace.build_traces(trace.read_spans(
            [tmp_path / "router_obs", tmp_path / "replica_obs"]))
        assert len(trees) == 1
        tree = trees[trace_id]
        assert tree.span_names == {"router.dispatch", "replica.request",
                                   "queue.wait"}
        assert len(tree.processes) == 2
        assert tree.cross_process_complete()
        assert [s["name"] for s in tree.roots] == ["router.dispatch"]
        # Chrome export covers every span plus metadata records.
        events = trace.chrome_trace_events(trees)
        xs = [e for e in events if e.get("ph") == "X"]
        assert len(xs) == 3
        assert {e["pid"] for e in xs} == {1, 2}

    def test_trace_report_cli(self, tmp_path):
        from eegnetreplication_tpu.obs import trace

        with obs.run(tmp_path / "obs", config={}) as jr:
            ctx = trace.TraceContext(trace.new_trace_id(), sampled=True)
            with trace.use(ctx):
                with trace.span("solo", journal=jr):
                    pass
        out = tmp_path / "chrome.json"
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "trace_report.py"),
             str(tmp_path / "obs"), "--chrome", str(out)],
            capture_output=True, text=True, timeout=120,
            env=dict(os.environ, EEGTPU_NO_LOG_FILE="1"))
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "solo" in proc.stdout
        assert json.loads(out.read_text())["traceEvents"]
        # The cross-process gate fails on a single-process trace.
        gate = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "trace_report.py"),
             str(tmp_path / "obs"), "--require-cross-process"],
            capture_output=True, text=True, timeout=120,
            env=dict(os.environ, EEGTPU_NO_LOG_FILE="1"))
        assert gate.returncode == 1


class TestSLO:
    def _monitor(self, jr, spec, **kw):
        from eegnetreplication_tpu.obs import slo

        clock = [0.0]
        kw.setdefault("window_s", 10.0)
        mon = slo.SLOMonitor(jr.metrics, spec, interval_s=0.0,
                             journal=jr, clock=lambda: clock[0], **kw)
        return mon, clock

    def test_parse_spec(self):
        from eegnetreplication_tpu.obs import slo

        objs = slo.parse_slo_spec(
            "p95_latency_ms<50,error_rate<0.01,availability>0.999")
        assert [o.metric for o in objs] == ["p95_latency_ms", "error_rate",
                                           "availability"]
        assert objs[0].threshold == 50.0 and objs[0].op == "<"
        with pytest.raises(ValueError):
            slo.parse_slo_spec("bogus_metric<1")
        with pytest.raises(ValueError):
            slo.parse_slo_spec("p95_latency_ms=50")
        with pytest.raises(ValueError):
            slo.parse_slo_spec("")

    def test_breach_and_recover_error_rate(self, tmp_path):
        with obs.run(tmp_path / "obs", config={}) as jr:
            mon, clock = self._monitor(jr, "error_rate<0.5")
            # Window 1: all errors -> breach.
            for _ in range(4):
                jr.metrics.inc("requests_total", status="error")
            clock[0] = 1.0
            states = mon.evaluate()
            assert mon.breached == ["error_rate<0.5"]
            assert states["error_rate<0.5"].value == 1.0
            # Healthy traffic arrives; the bad minute ages out of the
            # sliding window -> recovered.
            for _ in range(50):
                jr.metrics.inc("requests_total", status="ok")
            clock[0] = 12.0
            mon.evaluate()
            clock[0] = 13.0
            mon.evaluate()
            assert mon.breached == []
        events = schema.read_events(jr.events_path)
        kinds = [e["event"] for e in events
                 if e["event"].startswith("slo_")]
        assert kinds == ["slo_breach", "slo_recovered"]
        breach = [e for e in events if e["event"] == "slo_breach"][0]
        assert breach["objective"] == "error_rate<0.5"
        assert breach["value"] == 1.0
        summary = schema.event_summary(events)
        assert summary["slo_breaches"] == 1
        assert summary["worst_slo"] == "error_rate<0.5"
        assert summary["slo_breached_now"] == []
        assert not any("_schema_error" in e for e in events)

    def test_latency_percentile_objective(self, tmp_path):
        with obs.run(tmp_path / "obs", config={}) as jr:
            mon, clock = self._monitor(jr, "p95_latency_ms<50")
            for _ in range(40):
                jr.metrics.observe("request_latency_ms", 5.0)
            clock[0] = 1.0
            mon.evaluate()
            assert mon.breached == []
            for _ in range(100):
                jr.metrics.observe("request_latency_ms", 400.0)
            clock[0] = 2.0
            states = mon.evaluate()
            assert mon.breached == ["p95_latency_ms<50"]
            assert states["p95_latency_ms<50"].value > 50.0

    def test_no_evidence_is_vacuously_ok(self, tmp_path):
        with obs.run(tmp_path / "obs", config={}) as jr:
            mon, clock = self._monitor(jr, "error_rate<0.01,"
                                           "availability>0.99")
            clock[0] = 1.0
            mon.evaluate()
            assert mon.breached == []
            state = mon.state()
            assert state["breached"] == []
            assert all(o["value"] is None for o in state["objectives"])

    def test_availability_ignores_backpressure(self, tmp_path):
        """429s are load shedding, not unavailability: only admitted
        requests count against the availability objective."""
        with obs.run(tmp_path / "obs", config={}) as jr:
            mon, clock = self._monitor(jr, "availability>0.9")
            for _ in range(20):
                jr.metrics.inc("requests_total", status="ok")
            for _ in range(80):
                jr.metrics.inc("requests_total", status="rejected")
            clock[0] = 1.0
            states = mon.evaluate()
            assert mon.breached == []
            assert states["availability>0.9"].value == 1.0
