"""Telemetry subsystem tests: schema, metrics registry, run journal, and
the protocols' on-chip instrumentation end-to-end (all CPU).

The journal/metrics/schema trio replaces three ad-hoc measurement paths;
these tests pin the contracts that make that worthwhile: every emitted
event validates (no ``_schema_error`` ever appears), a protocol run under
a run context yields a complete ``events.jsonl`` + ``metrics.json``, a
device fault is journaled with its retry wall, and ``scripts/obs_report.py``
renders what the journal wrote.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from eegnetreplication_tpu import obs
from eegnetreplication_tpu.config import DEFAULT_TRAINING, Paths
from eegnetreplication_tpu.obs import MetricsRegistry, schema
from synthetic import make_loader

REPO = Path(__file__).resolve().parents[1]
CFG = DEFAULT_TRAINING.replace(batch_size=16)


def tiny_loader():
    return make_loader(n_trials=24, n_channels=4, n_times=32, class_sep=1.5)


class TestSchema:
    def test_event_missing_required_keys_raises(self):
        with pytest.raises(schema.SchemaError, match="missing required"):
            schema.validate_event({"event": "epoch", "t": 1.0,
                                   "run_id": "r", "epoch": 1})

    def test_unknown_event_type_allowed_with_base_keys(self):
        schema.validate_event({"event": "custom_probe", "t": 1.0,
                               "run_id": "r", "anything": True})

    def test_complete_stream_needs_start_and_end(self):
        ep = {"event": "epoch", "t": 1.0, "run_id": "r", "epoch": 1,
              "total_epochs": 1, "train_loss": 1.0, "val_loss": 1.0,
              "val_acc": 50.0, "grad_norm": 0.5, "n_folds": 4}
        with pytest.raises(schema.SchemaError, match="run_start"):
            schema.validate_events([ep])
        # but a live/partial stream is fine with complete=False
        schema.validate_events([ep], complete=False)

    def test_metrics_validation(self):
        good = MetricsRegistry()
        good.inc("n", 2.0)
        schema.validate_metrics(good.snapshot("rid"))
        with pytest.raises(schema.SchemaError):
            schema.validate_metrics({"schema_version": 1, "run_id": "r",
                                     "utc": "t", "counters": {},
                                     "gauges": {}})  # histograms missing

    def test_bench_writer_stamps_and_validates(self, tmp_path):
        path = tmp_path / "BENCH_X.json"
        schema.write_json_artifact(path, {"platform": "cpu", "value": 1.5})
        rec = json.loads(path.read_text())
        assert rec["schema_version"] == schema.SCHEMA_VERSION
        assert "utc" in rec and rec["value"] == 1.5
        schema.validate_bench(rec)
        with pytest.raises(schema.SchemaError, match="platform"):
            schema.write_json_artifact(tmp_path / "bad.json", {"value": 2})


class TestMetricsRegistry:
    def test_counter_accumulates_per_label_series(self):
        m = MetricsRegistry()
        m.inc("fold_epochs_total", 10)
        m.inc("fold_epochs_total", 26)
        m.inc("fold_epochs_total", 5, group="1")
        assert m.get("fold_epochs_total") == 36
        assert m.get("fold_epochs_total", group="1") == 5
        with pytest.raises(ValueError, match="cannot decrease"):
            m.inc("fold_epochs_total", -1)

    def test_gauge_last_write_wins(self):
        m = MetricsRegistry()
        m.set("hbm_bytes_in_use", 100, device="0")
        m.set("hbm_bytes_in_use", 200, device="0")
        m.set("hbm_bytes_in_use", 50, device="1")
        assert m.get("hbm_bytes_in_use", device="0") == 200
        assert m.get("hbm_bytes_in_use", device="1") == 50

    def test_histogram_aggregation(self):
        m = MetricsRegistry()
        for v in (1.0, 3.0, 2.0):
            m.observe("chunk_wall_s", v)
        snap = m.snapshot("rid")
        [h] = snap["histograms"]["chunk_wall_s"]
        assert h["count"] == 3 and h["sum"] == 6.0
        assert h["min"] == 1.0 and h["max"] == 3.0 and h["mean"] == 2.0

    def test_kind_collision_rejected(self):
        m = MetricsRegistry()
        m.inc("x")
        with pytest.raises(ValueError, match="different kind"):
            m.set("x", 1.0)

    def test_flush_roundtrip(self, tmp_path):
        m = MetricsRegistry()
        m.inc("a", 2)
        m.set("b", 3.5)
        m.observe("c", 0.25)
        path = m.flush(tmp_path / "metrics.json", run_id="rid")
        rec = schema.read_metrics(path)
        assert rec["run_id"] == "rid"
        assert rec["counters"]["a"][0]["value"] == 2


class TestRunJournal:
    def test_run_context_roundtrip(self, tmp_path):
        with obs.run(tmp_path, config={"epochs": 2}, note="test") as jr:
            assert obs.current() is jr
            jr.event("compile_begin", what="x")
            jr.event("compile_end", what="x", elapsed_s=0.5)
            jr.metrics.inc("fold_epochs_total", 8)
        events = schema.read_events(jr.events_path)
        kinds = [e["event"] for e in events]
        assert kinds[0] == "run_start" and kinds[-1] == "run_end"
        assert events[0]["config"] == {"epochs": 2}
        assert events[0]["device_kind"]
        assert events[-1]["status"] == "ok"
        assert not any("_schema_error" in e for e in events)
        metrics = schema.read_metrics(jr.metrics_path)
        assert metrics["counters"]["fold_epochs_total"][0]["value"] == 8
        assert metrics["gauges"]["wall_seconds"][0]["value"] >= 0

    def test_exception_journals_error_status(self, tmp_path):
        with pytest.raises(RuntimeError, match="boom"):
            with obs.run(tmp_path) as jr:
                raise RuntimeError("boom")
        events = schema.read_events(jr.events_path)
        assert events[-1]["status"] == "error"
        assert "boom" in events[-1]["error"]

    def test_no_context_is_inert(self):
        jr = obs.current()
        assert not jr.active
        jr.event("epoch")  # must not raise or write anywhere
        jr.metrics.inc("x")
        jr.run_end()

    def test_dataclass_config_with_nested_path_serializes(self, tmp_path):
        import dataclasses

        @dataclasses.dataclass
        class Cfg:
            out: Path
            arr: object

        with obs.run(tmp_path,
                     config=Cfg(out=tmp_path / "x", arr=np.arange(3))) as jr:
            pass
        events = schema.read_events(jr.events_path)
        cfg = events[0]["config"]
        assert cfg["out"] == str(tmp_path / "x")
        assert isinstance(cfg["arr"], str)  # repr-coerced, not a crash

    def test_unserializable_event_field_does_not_raise(self, tmp_path):
        with obs.run(tmp_path) as jr:
            jr.event("custom_probe", blob={1, 2})  # a set: not JSON
        events = schema.read_events(jr.events_path)
        probe = next(e for e in events if e["event"] == "custom_probe")
        assert isinstance(probe["blob"], str)

    def test_invalid_event_is_flagged_not_fatal(self, tmp_path):
        with obs.run(tmp_path) as jr:
            jr.event("epoch", epoch=1)  # missing most required keys
        events = schema.read_events(jr.events_path)
        bad = [e for e in events if e["event"] == "epoch"]
        assert bad and "_schema_error" in bad[0]


class TestProtocolTelemetry:
    def _run_ws(self, tmp_path, **kw):
        from eegnetreplication_tpu.training.protocols import (
            within_subject_training,
        )

        with obs.run(tmp_path / "obs", config=CFG) as jr:
            result = within_subject_training(
                epochs=3, config=CFG, loader=tiny_loader(), subjects=(1,),
                paths=Paths.from_root(tmp_path), seed=0, save_models=False,
                **kw)
        return result, jr

    def test_ws_smoke_writes_complete_journal(self, tmp_path):
        result, jr = self._run_ws(tmp_path, checkpoint_every=2)
        events = schema.read_events(jr.events_path)
        kinds = [e["event"] for e in events]
        assert kinds[0] == "run_start" and kinds[-1] == "run_end"
        assert "train_setup" in kinds and "compile_end" in kinds
        assert not any("_schema_error" in e for e in events)
        setup = next(e for e in events if e["event"] == "train_setup")
        assert setup["n_folds"] == 4 and setup["epochs"] == 3
        assert setup["real_train_samples"] > 0
        epochs = [e for e in events if e["event"] == "epoch"]
        assert len(epochs) == 3  # chunked path journals every epoch live
        for ev in epochs:
            assert np.isfinite(ev["train_loss"])
            assert np.isfinite(ev["val_loss"])
            assert ev["grad_norm"] > 0  # real gradients flowed
        metrics = schema.read_metrics(jr.metrics_path)
        assert metrics["counters"]["fold_epochs_total"][0]["value"] == 12
        assert metrics["histograms"]["chunk_wall_s"][0]["count"] == 2

    def test_ws_single_program_journals_epochs_posthoc(self, tmp_path):
        result, jr = self._run_ws(tmp_path)  # 3 epochs -> one fused program
        events = schema.read_events(jr.events_path)
        epochs = [e for e in events if e["event"] == "epoch"]
        assert len(epochs) == 3
        assert all(e["grad_norm"] > 0 for e in epochs)
        compile_end = next(e for e in events if e["event"] == "compile_end")
        assert compile_end["includes_execution"] is True

    def test_device_fault_journaled_with_retry_wall(self, tmp_path,
                                                    monkeypatch):
        from eegnetreplication_tpu.training import protocols as P

        monkeypatch.setattr(P, "_fold_batch_limit_path",
                            lambda: tmp_path / "limits.json")
        # 4 folds at fold_batch=3: group 0 (3 folds) exceeds the injected
        # 2-fold device limit, faults, halves to 1, completes all folds.
        result, jr = self._run_ws(tmp_path, fold_batch=3,
                                  _fault_if_folds_over=2)
        events = schema.read_events(jr.events_path)
        faults = [e for e in events if e["event"] == "device_fault"]
        assert faults, "the injected fault must be journaled"
        assert faults[0]["retry_fold_batch"] == 1
        assert "UNAVAILABLE" in faults[0]["error"]
        metrics = schema.read_metrics(jr.metrics_path)
        assert metrics["counters"]["device_fault_retries"][0]["value"] >= 1
        # ADVICE r5: the faulted attempt's wall is accounted, both in the
        # metric and in the protocol's wall_seconds.
        assert metrics["counters"]["fault_retry_wall_s"][0]["value"] > 0
        assert result.fault_retry_wall_s > 0
        assert result.wall_seconds >= result.fault_retry_wall_s

    def test_obs_report_renders_run(self, tmp_path):
        _, jr = self._run_ws(tmp_path, checkpoint_every=2)
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "obs_report.py"),
             str(tmp_path / "obs")],
            capture_output=True, text=True, timeout=120,
            env=dict(os.environ, EEGTPU_NO_LOG_FILE="1"))
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert jr.run_id in proc.stdout
        assert "within_subject" in proc.stdout
        proc_json = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "obs_report.py"),
             "--json", str(jr.dir)],
            capture_output=True, text=True, timeout=120,
            env=dict(os.environ, EEGTPU_NO_LOG_FILE="1"))
        assert proc_json.returncode == 0, proc_json.stderr[-2000:]
        summary = json.loads(proc_json.stdout.strip().splitlines()[-1])
        assert summary["status"] == "ok"
        assert summary["n_epoch_events"] == 3
        assert summary["fold_epochs_total"] == 12
