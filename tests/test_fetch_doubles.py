"""Execute the network fetchers against in-process doubles.

The two fetchers were the inventory's only "partial" rows — faithful code
that had never executed (no egress, no kagglehub/moabb in this image).
Like ``fake_mne``, these doubles implement exactly the API slice each
fetcher touches, so the fetcher LOGIC (cache mirroring, per-run ``.fif``
layout, session naming, politeness pacing) runs in CI; only the network
transport itself remains unverifiable here.
"""

import sys
import types
from pathlib import Path
from unittest import mock

import pytest

from eegnetreplication_tpu.config import Paths


@pytest.fixture
def tmp_paths(tmp_path):
    return Paths.from_root(tmp_path)


class TestKaggleFetcher:
    def _install_kagglehub(self, cache: Path, calls: list):
        mod = types.ModuleType("kagglehub")

        def dataset_download(dataset):
            calls.append(dataset)
            return str(cache)

        mod.dataset_download = dataset_download
        return mock.patch.dict(sys.modules, {"kagglehub": mod})

    def test_downloads_and_mirrors_cache(self, tmp_path, tmp_paths):
        from eegnetreplication_tpu.fetch import KAGGLE_DATASET, fetch_from_kaggle

        cache = tmp_path / "kaggle_cache"
        (cache / "Train").mkdir(parents=True)
        (cache / "Train" / "A01T.gdf").write_bytes(b"gdf-bytes")
        (cache / "TrueLabels").mkdir()
        (cache / "TrueLabels" / "A01E.mat").write_bytes(b"mat-bytes")
        calls: list = []
        with self._install_kagglehub(cache, calls):
            out = fetch_from_kaggle(paths=tmp_paths)
        assert calls == [KAGGLE_DATASET]
        assert out == tmp_paths.data_raw
        assert (out / "Train" / "A01T.gdf").read_bytes() == b"gdf-bytes"
        assert (out / "TrueLabels" / "A01E.mat").read_bytes() == b"mat-bytes"

    def test_refetch_replaces_stale_tree(self, tmp_path, tmp_paths):
        from eegnetreplication_tpu.fetch import fetch_from_kaggle

        cache = tmp_path / "kaggle_cache"
        (cache / "Train").mkdir(parents=True)
        (cache / "Train" / "A01T.gdf").write_bytes(b"fresh")
        stale = tmp_paths.data_raw / "Train"
        stale.mkdir(parents=True)
        (stale / "orphan.gdf").write_bytes(b"old")
        with self._install_kagglehub(cache, []):
            fetch_from_kaggle(paths=tmp_paths)
        assert (tmp_paths.data_raw / "Train" / "A01T.gdf").exists()
        assert not (stale / "orphan.gdf").exists()  # dir replaced wholesale


class TestMoabbFetcher:
    def _install_moabb(self, subjects=(1,), runs=("run_0",)):
        saved: list[Path] = []

        class FakeRaw:
            def save(self, path, overwrite=False):
                assert overwrite is True
                Path(path).write_bytes(b"raw-fif")
                saved.append(Path(path))

        class FakeBNCI2014001:
            subject_list = list(subjects)

            def get_data(self, subjects):
                (subject,) = subjects
                return {subject: {
                    "0train": {r: FakeRaw() for r in runs},
                    "1test": {r: FakeRaw() for r in runs},
                }}

        datasets_mod = types.ModuleType("moabb.datasets")
        datasets_mod.BNCI2014001 = FakeBNCI2014001
        moabb_mod = types.ModuleType("moabb")
        moabb_mod.datasets = datasets_mod
        patcher = mock.patch.dict(sys.modules, {
            "moabb": moabb_mod, "moabb.datasets": datasets_mod})
        return patcher, saved

    def test_per_run_fif_layout(self, tmp_paths):
        from eegnetreplication_tpu.fetch import fetch_from_moabb

        patcher, saved = self._install_moabb()
        # the 1 s politeness sleep is the reference's contract; stub it so
        # the test doesn't pay it, but record that it was invoked per run
        sleeps: list = []
        with patcher, mock.patch("eegnetreplication_tpu.fetch.time") as t:
            t.sleep = sleeps.append
            out = fetch_from_moabb(paths=tmp_paths)
        assert out == tmp_paths.data_moabb
        train = tmp_paths.data_moabb / "Train" / "A01T_run_0.fif"
        evald = tmp_paths.data_moabb / "Eval" / "A01E_run_0.fif"
        assert train.read_bytes() == b"raw-fif"
        assert evald.read_bytes() == b"raw-fif"
        assert len(saved) == 2 and len(sleeps) == 2

    def test_unknown_dataset_rejected(self, tmp_paths):
        from eegnetreplication_tpu.fetch import fetch_from_moabb

        patcher, _ = self._install_moabb()
        with patcher, pytest.raises(ValueError, match="Unknown moabb"):
            fetch_from_moabb(dataset="NotADataset", paths=tmp_paths)
