"""Zero-SPOF front tier (``eegnetreplication_tpu/serve/cells/ha.py``).

Covers the ISSUE-20 surface: the fencing lease (token bumped on every
acquisition, never on renew; torn/alien files read as *no lease*), the
durable affinity WAL (writer fold == replay exactness through size
rotation with snapshot-marker compaction; torn-tail records skipped on
replay AND sealed before a successor's first append), the in-process
active/standby pair (standby tails the WAL without echoing it, promotes
only after lease expiry, and the journal pins ``affinity_replay``
BEFORE the ``front_lease takeover``), the observability fold of the
four new events at the deepest cells-run nesting, and the
``serve_bench.py --ha`` tier-1 selftest (SIGKILL'd active front,
rolling upgrade under load, mirror-spool restore).

Everything above the selftest is pure stdlib + threads — no JAX, no
subprocesses — so the suite stays fast; the end-to-end truth (real
fronts, real SIGKILL, real engines) lives in the selftest leg and the
chaos drill's ``front.failover``/``cell.upgrade`` legs.
"""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

from eegnetreplication_tpu.obs import agg as obs_agg
from eegnetreplication_tpu.obs import journal as obs_journal
from eegnetreplication_tpu.obs import schema
from eegnetreplication_tpu.serve.cells.front import CellFront
from eegnetreplication_tpu.serve.cells.membership import CellMember
from eegnetreplication_tpu.serve.cells.ha import (
    AffinityWAL,
    FencingLease,
    HAController,
)

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture
def journal(tmp_path):
    with obs_journal.run(tmp_path / "obs", config={}) as jr:
        yield jr


def _events(jr, kind=None):
    events = schema.read_events(jr.events_path, complete=False)
    if kind is None:
        return events
    return [e for e in events if e["event"] == kind]


def _wait(predicate, timeout_s=10.0, poll_s=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(poll_s)
    return predicate()


# ---------------------------------------------------------------------------
# Fencing lease: shared storage as the arbiter.


class TestFencingLease:
    def test_acquire_bumps_token_every_epoch(self, tmp_path):
        lease = FencingLease(tmp_path / "lease.json", owner="f0",
                             ttl_s=5.0)
        assert lease.try_acquire()
        assert lease.token == 1
        # Re-acquiring our OWN lease is a new fencing epoch (a restart
        # lost the in-memory table) — the token must bump again.
        assert lease.try_acquire()
        assert lease.token == 2

    def test_fresh_lease_blocks_other_owner(self, tmp_path):
        a = FencingLease(tmp_path / "lease.json", owner="f0", ttl_s=5.0)
        b = FencingLease(tmp_path / "lease.json", owner="f1", ttl_s=5.0)
        assert a.try_acquire()
        assert not b.try_acquire()
        assert b.token == 0

    def test_expired_lease_taken_with_monotonic_token(self, tmp_path):
        a = FencingLease(tmp_path / "lease.json", owner="f0", ttl_s=0.05)
        b = FencingLease(tmp_path / "lease.json", owner="f1", ttl_s=0.05)
        assert a.try_acquire()
        time.sleep(0.1)
        assert b.try_acquire()
        # The taker continues the dead owner's token sequence — the
        # fencing order is total across owners.
        assert b.token == a.token + 1

    def test_renew_keeps_token_and_detects_loss(self, tmp_path):
        a = FencingLease(tmp_path / "lease.json", owner="f0", ttl_s=0.05)
        b = FencingLease(tmp_path / "lease.json", owner="f1", ttl_s=0.05)
        assert a.try_acquire()
        assert a.renew() == "ok"
        assert a.token == 1
        time.sleep(0.1)
        assert b.try_acquire()
        # The old active's next renew sees the usurper and must fence.
        assert a.renew() == "lost"

    def test_torn_lease_reads_as_absent(self, tmp_path):
        path = tmp_path / "lease.json"
        path.write_text('{"owner": "f0", "tok')
        lease = FencingLease(path, owner="f1", ttl_s=5.0)
        assert lease.read() is None
        assert lease.expired()
        assert lease.try_acquire()

    def test_release_only_deletes_own_lease(self, tmp_path):
        a = FencingLease(tmp_path / "lease.json", owner="f0", ttl_s=5.0)
        b = FencingLease(tmp_path / "lease.json", owner="f1", ttl_s=5.0)
        assert a.try_acquire()
        b.release()  # not ours: must be a no-op
        assert a.read()["owner"] == "f0"
        a.release()
        assert a.read() is None


# ---------------------------------------------------------------------------
# Affinity WAL: replay exactness is the whole contract.


class TestAffinityWAL:
    def _mutate(self, wal, n=0):
        wal.append("assign", "s1", "c0")
        wal.append("assign", "s2", "c1")
        wal.append("flip", "s2", "c0", resync=True)
        wal.append("assign", "s3", "c1")
        wal.append("drop", "s3")
        for i in range(n):
            wal.append("assign", f"bulk{i:04d}", f"c{i % 3}")

    def test_replay_matches_writer_fold(self, tmp_path):
        wal = AffinityWAL(tmp_path / "affinity.wal")
        self._mutate(wal)
        wal.close()
        affinity, resync, n = AffinityWAL(tmp_path / "affinity.wal").replay()
        assert affinity == {"s1": "c0", "s2": "c0"}
        assert resync == {"s2"}
        assert n == 5

    def test_rotation_compacts_exactly(self, tmp_path):
        wal = AffinityWAL(tmp_path / "affinity.wal", max_bytes=2048)
        self._mutate(wal, n=200)  # forces several rotations
        writer_state = dict(wal._state)
        writer_resync = set(wal._resync)
        wal.close()
        assert (tmp_path / "affinity.wal.1").exists()
        # The live file opens with the snapshot marker followed by the
        # compacted table — archives are pure history.
        first = json.loads(
            (tmp_path / "affinity.wal").read_text().splitlines()[0])
        assert first["op"] == "snapshot"
        affinity, resync, _ = AffinityWAL(tmp_path / "affinity.wal").replay()
        assert affinity == writer_state
        assert resync == writer_resync

    def test_torn_tail_skipped_and_sealed(self, tmp_path):
        path = tmp_path / "affinity.wal"
        wal = AffinityWAL(path)
        wal.append("assign", "s1", "c0")
        wal.append("assign", "s2", "c1")
        wal.close()
        # A mid-append death leaves a torn final line with no newline.
        with open(path, "a", encoding="utf-8") as fh:
            fh.write('{"op":"assign","session":"s3","ce')
        affinity, resync, n = AffinityWAL(path).replay()
        assert affinity == {"s1": "c0", "s2": "c1"}
        assert n == 2
        # A successor's first append must not be spliced into (and lost
        # with) the torn line: the lazy open seals it first.
        successor = AffinityWAL(path)
        successor.append("assign", "s4", "c0")
        successor.close()
        affinity, _, _ = AffinityWAL(path).replay()
        assert affinity == {"s1": "c0", "s2": "c1", "s4": "c0"}

    def test_reopened_writer_seeds_fold_for_compaction(self, tmp_path):
        path = tmp_path / "affinity.wal"
        wal = AffinityWAL(path)
        self._mutate(wal)
        wal.close()
        # A restarted front re-opens the same WAL; its next rotation
        # must compact the REAL table, not an empty one.
        reopened = AffinityWAL(path, max_bytes=1)
        reopened.append("assign", "s9", "c2")  # triggers rotation
        reopened.close()
        affinity, resync, _ = AffinityWAL(path).replay()
        assert affinity == {"s1": "c0", "s2": "c0", "s9": "c2"}
        assert resync == {"s2"}

    def test_fingerprint_tracks_appends(self, tmp_path):
        wal = AffinityWAL(tmp_path / "affinity.wal")
        fp0 = wal.fingerprint()
        wal.append("assign", "s1", "c0")
        fp1 = wal.fingerprint()
        assert fp1 != fp0
        wal.close()


# ---------------------------------------------------------------------------
# Active/standby pair, in-process: promotion order and table exactness.


class TestHAPairPromotion:
    def test_standby_tails_then_promotes_exactly(self, tmp_path, journal):
        ha_dir = tmp_path / "ha"
        # The membership poller never runs (the fronts are not started),
        # so an unreachable placeholder cell is inert.
        f1 = CellFront([CellMember("c0", "http://127.0.0.1:1",
                                   journal=journal)],
                       port=0, poll_s=60.0, journal=journal)
        ha1 = HAController(f1, ha_dir, owner="f1", url="http://f1",
                           ttl_s=0.5, poll_s=0.05, journal=journal).start()
        try:
            assert ha1.role == "active"
            assert ha1.leader_hint() == "http://f1"
            # Mutations flow through the front's leader-gated WAL hook.
            with f1._table_lock:
                f1._affinity["s1"] = "c0"
                f1._wal_append("assign", "s1", "c0")
                f1._affinity["s2"] = "c1"
                f1._wal_append("assign", "s2", "c1")
                f1._affinity["s2"] = "c0"
                f1._needs_resync.add("s2")
                f1._wal_append("flip", "s2", "c0", resync=True)

            f2 = CellFront([CellMember("c0", "http://127.0.0.1:1",
                                       journal=journal)],
                           port=0, poll_s=60.0, journal=journal)
            ha2 = HAController(f2, ha_dir, owner="f2", url="http://f2",
                               ttl_s=0.5, poll_s=0.05,
                               journal=journal).start()
            try:
                assert ha2.role == "standby"
                # The standby tails the WAL into its routing table...
                assert _wait(lambda: f2._affinity == {"s1": "c0",
                                                      "s2": "c0"})
                assert f2._needs_resync == {"s2"}
                # ...but must never echo records back into the log.
                assert ha2.wal.appended == 0
                f2._wal_append("assign", "sX", "c9")
                assert ha2.wal.appended == 0

                # Crash the active (no release): the standby may promote
                # only after the lease expires.
                ha1.close(release=False)
                assert not ha1.lease.expired()
                assert ha2.role == "standby"
                assert _wait(lambda: ha2.role == "active", timeout_s=10.0)
                assert f2._affinity == {"s1": "c0", "s2": "c0"}
                assert f2._needs_resync == {"s2"}
                assert ha2.lease.token == ha1.lease.token + 1
                assert f2.is_leader
            finally:
                ha2.close()
        finally:
            ha1.close(release=False)

        kinds = [(e["event"], e.get("action")) for e in _events(journal)
                 if e["event"] in ("front_lease", "affinity_replay")]
        assert ("front_lease", "acquire") in kinds
        assert ("front_lease", "standby") in kinds
        # The journal pins replay-before-takeover: the new active's
        # table is exact BEFORE it may serve a single request.
        replay_at = kinds.index(("affinity_replay", None))
        takeover_at = kinds.index(("front_lease", "takeover"))
        assert replay_at < takeover_at
        replay = _events(journal, "affinity_replay")[0]
        assert replay["n_sessions"] == 2
        assert replay["n_resync"] == 1


# ---------------------------------------------------------------------------
# Observability fold: the four new events through the deepest nesting.

_T0 = 1700000000.0

_RUN_START = {"event": "run_start", "schema_version": 1, "git_sha": "0" * 8,
              "platform": "cpu", "device_kind": "cpu", "n_devices": 1,
              "config": {}}


def _write_run(run_dir, events):
    run_dir.mkdir(parents=True)
    lines = [json.dumps({"t": _T0 + i, "run_id": run_dir.name, **ev})
             for i, ev in enumerate(events)]
    (run_dir / "events.jsonl").write_text("\n".join(lines) + "\n")


class TestAggHAFold:
    def _populate(self, root):
        # Front journal at metricsDir depth 1; a cell member's replica
        # journal at the cells-run depth THREE (c0_obs/<cell_run>/
        # replica_obs/<replica_run>) — discovery must walk both.
        _write_run(root / "f1_obs" / "run_front", [
            _RUN_START | {"run_id": "run_front"},
            {"event": "front_lease", "action": "standby", "owner": "f1",
             "token": 1},
            {"event": "affinity_replay", "n_records": 3, "n_sessions": 2,
             "n_resync": 1},
            {"event": "front_lease", "action": "takeover", "owner": "f1",
             "token": 2},
            {"event": "spool_mirror", "action": "restored",
             "session": "s1", "cell": "c0"},
            {"event": "session_failover", "session": "s9",
             "from_cell": "c0", "to_cell": "c1",
             "action": "spool_error"},
        ])
        _write_run(root / "c0_obs" / "run_cell" / "replica_obs"
                   / "run_replica", [
            _RUN_START | {"run_id": "run_replica"},
            {"event": "cell_upgrade", "cell": "c0", "action": "drain"},
            {"event": "cell_upgrade", "cell": "c0", "action": "undrain"},
            {"event": "cell_upgrade", "cell": "c1", "action": "drain"},
            {"event": "cell_upgrade", "cell": "c1", "action": "rollback",
             "recovered": 1, "digest": "abc"},
        ])

    def test_fleet_state_folds_ha_events(self, tmp_path):
        self._populate(tmp_path)
        snap = obs_agg.Aggregator([tmp_path]).poll()
        assert snap["n_runs"] == 2
        by_id = {r["run_id"]: r for r in snap["runs"]}
        front = by_id["run_front"]
        assert front["lease"] == {"owner": "f1", "token": 2,
                                  "role": "active", "takeovers": 1,
                                  "fenced": 0, "replays": 1}
        assert front["mirror_restores"] == 1
        replica = by_id["run_replica"]
        assert replica["upgrade"] == {"done": 1, "rollbacks": 1,
                                      "draining": None}

    def test_event_summary_reports_ha_counters(self, tmp_path):
        self._populate(tmp_path)
        events = []
        for path in sorted(tmp_path.rglob("events.jsonl")):
            events.extend(schema.read_events(path, complete=False))
        summary = schema.event_summary(events)
        assert summary["lease_takeovers"] == 1
        assert summary["front_fenced"] == 0
        assert summary["affinity_replays"] == 1
        assert summary["cells_upgraded"] == 1
        assert summary["upgrade_rollbacks"] == 1
        assert summary["mirror_restores"] == 1
        assert summary["spool_errors"] == 1


# ---------------------------------------------------------------------------
# End-to-end truth: the --ha selftest (real fronts, real SIGKILL, real
# engines) must pass and leave a gate-shaped record behind.


class TestHABenchSelftest:
    def test_ha_selftest_passes(self, tmp_path):
        out = tmp_path / "BENCH_HA_selftest.json"
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "serve_bench.py"),
             "--ha", "--selftest", "--haOut", str(out)],
            capture_output=True, text=True, timeout=540,
            env=dict(os.environ, EEGTPU_NO_LOG_FILE="1",
                     EEGTPU_PLATFORM="cpu", JAX_PLATFORMS="cpu"))
        assert proc.returncode == 0, (proc.stdout[-4000:]
                                      + proc.stderr[-2000:])
        assert "SELFTEST PASS" in proc.stdout
        record = json.loads(out.read_text())
        failover = record["failover"]
        assert failover["lease_takeovers"] >= 1
        assert failover["takeover_before_first_request"] == 1
        assert failover["duplicate_conflicts"] == 0
        assert failover["decisions_equal"] == 1
        assert failover["bulk"]["failures"] == 0
        assert failover["bulk"]["max_hint_retries"] <= 1
        upgrade = record["upgrade_leg"]
        assert upgrade["upgrade"]["status"] == "ok"
        assert upgrade["upgrade"]["upgraded"] == ["c0", "c1"]
        assert upgrade["window_expirations"] == 0
        assert upgrade["serialized_ok"] == 1
        mirror = record["mirror_leg"]
        assert mirror["mirror_restores"] >= 1
        assert mirror["decisions_equal"] == 1
