"""Cross-check the C++ GDF reader against the pure-numpy implementation.

The numpy reader (``data/gdf.py``) is the behavioral spec; the native library
(``native/gdf_reader.cc``) must produce identical arrays for the same bytes.
Skipped when no C++ toolchain is available to build the library.
"""

import tempfile
import unittest
from pathlib import Path

import numpy as np

from eegnetreplication_tpu.data import gdf_native
from eegnetreplication_tpu.data.gdf import read_gdf, read_gdf_python, write_gdf

HAVE_NATIVE = gdf_native.ensure_built()


@unittest.skipUnless(HAVE_NATIVE, "native GDF library not buildable here")
class TestNativeGDFParity(unittest.TestCase):
    def _make(self, d, version, with_events=True):
        rng = np.random.RandomState(11)
        sig = rng.uniform(-0.99, 0.99, (25, 250 * 5)).astype(np.float32)
        pos = np.array([10, 400, 900]) if with_events else None
        typ = np.array([768, 769, 783]) if with_events else None
        return write_gdf(Path(d) / f"x{version[0]}.gdf", sig, 250.0,
                         labels=[f"EEG-{i}" for i in range(25)],
                         event_pos=pos, event_typ=typ, version=version)

    def test_parity_both_versions(self):
        # 1.92 exercises the GDF 1.90-1.93 corner: v2-style fixed/channel
        # headers but the v1 event-table layout (the switch is at 1.94).
        for version in ("2.20", "1.92", "1.25"):
            with tempfile.TemporaryDirectory() as d:
                p = self._make(d, version)
                py = read_gdf_python(p)
                nat = gdf_native.read_gdf(p)
            np.testing.assert_array_equal(nat.signals, py.signals)
            np.testing.assert_array_equal(nat.event_pos, py.event_pos)
            np.testing.assert_array_equal(nat.event_typ, py.event_typ)
            self.assertEqual(nat.labels, py.labels)
            self.assertEqual(nat.sfreq, py.sfreq)
            self.assertEqual(nat.n_channels, py.n_channels)

    def test_no_events(self):
        with tempfile.TemporaryDirectory() as d:
            p = self._make(d, "2.20", with_events=False)
            nat = gdf_native.read_gdf(p)
        self.assertEqual(len(nat.event_pos), 0)

    def test_read_gdf_dispatches_to_native(self):
        with tempfile.TemporaryDirectory() as d:
            p = self._make(d, "2.20")
            rec = read_gdf(p, prefer_native=True)
        self.assertEqual(rec.signals.shape, (25, 1250))

    def test_native_error_reporting(self):
        with tempfile.TemporaryDirectory() as d:
            bad = Path(d) / "bad.gdf"
            bad.write_bytes(b"\x00" * 512)
            with self.assertRaises(ValueError):
                gdf_native.read_gdf(bad)


if __name__ == "__main__":
    unittest.main()
