"""Durable streaming sessions (``serve/sessions/`` + the stateful EMS
carrier in ``ops/ems.py``).

Covers the ISSUE-7 acceptance surface: streaming-vs-offline EMS byte
parity under arbitrary chunking (including one sample at a time), the
session window slider and its decided-frontier snapshot semantics, the
store's stamped/rotated/quarantined snapshot chain, the HTTP session API
(open/samples/state/close, per-window deadlines with graceful
degradation), SIGTERM-drain snapshot + ``--resume`` restore with a
byte-identical continued decision stream, and the ``stream_bench.py
--selftest`` tier-1 leg (paced 250 Hz replay parity + supervised
SIGKILL-mid-stream resume).
"""

import json
import os
import re
import subprocess
import sys
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from eegnetreplication_tpu.models import EEGNet  # noqa: E402
from eegnetreplication_tpu.obs import journal as obs_journal  # noqa: E402
from eegnetreplication_tpu.obs import schema  # noqa: E402
from eegnetreplication_tpu.ops.ems import (  # noqa: E402
    StreamingEMS,
    raw_exponential_moving_standardize,
)
from eegnetreplication_tpu.resil import inject  # noqa: E402
from eegnetreplication_tpu.serve.engine import InferenceEngine  # noqa: E402
from eegnetreplication_tpu.serve.service import ServeApp  # noqa: E402
from eegnetreplication_tpu.serve.sessions import (  # noqa: E402
    SessionStore,
    StreamSession,
    WindowDecision,
)
from eegnetreplication_tpu.training.checkpoint import (  # noqa: E402
    save_checkpoint,
)

REPO = Path(__file__).resolve().parent.parent

C, T = 4, 64
HOP = 16
BLOCK = 256


@pytest.fixture(scope="module")
def recording():
    rng = np.random.RandomState(7)
    x = rng.randn(C, 2000).astype(np.float32) * 5.0
    x += 9.0  # DC offset the standardization must absorb
    return x


def _offline_std(x, init_block=BLOCK):
    return raw_exponential_moving_standardize(
        x, init_block_size=init_block, method="scan")


def _offline_windows(std, window=T, hop=HOP):
    wins = []
    k = 0
    while k * hop + window <= std.shape[1]:
        wins.append(std[:, k * hop:k * hop + window])
        k += 1
    return np.stack(wins) if wins else np.zeros((0, std.shape[0], window),
                                                np.float32)


def _stream(x, chunk_sizes, init_block=BLOCK):
    ems = StreamingEMS(x.shape[0], init_block_size=init_block)
    outs, pos, i = [], 0, 0
    while pos < x.shape[1]:
        n = chunk_sizes[i % len(chunk_sizes)]
        i += 1
        outs.append(ems.push(x[:, pos:pos + n]))
        pos += min(n, x.shape[1] - pos)
    return np.concatenate(outs, axis=1), ems


class TestStreamingEMS:
    """ISSUE-7 satellite: streaming-vs-offline EMS parity must be BYTE
    identical — approximate equality would make mid-stream resume drift
    from an uninterrupted run."""

    @pytest.mark.parametrize("sizes", [[1], [7], [250], [2000],
                                       [1, 2, 3, 5, 8, 13, 255]])
    def test_chunked_byte_identical_to_one_shot(self, recording, sizes):
        got, _ = _stream(recording, sizes)
        ref = _offline_std(recording)
        assert got.shape == ref.shape
        np.testing.assert_array_equal(got, ref)

    def test_state_roundtrip_continues_byte_identically(self, recording):
        ems1 = StreamingEMS(C, init_block_size=BLOCK)
        head = ems1.push(recording[:, :900])
        # Serialize mid-stream, rebuild, continue on the clone.
        clone = StreamingEMS.from_state(ems1.state_arrays())
        tail = clone.push(recording[:, 900:])
        got = np.concatenate([head, tail], axis=1)
        np.testing.assert_array_equal(got, _offline_std(recording))
        assert clone.n_seen == recording.shape[1]

    def test_pre_seed_state_roundtrip(self, recording):
        """A snapshot taken BEFORE the seed block filled must preserve the
        raw buffer so seeding happens identically after restore."""
        ems1 = StreamingEMS(C, init_block_size=BLOCK)
        assert ems1.push(recording[:, :100]).shape == (C, 0)
        clone = StreamingEMS.from_state(ems1.state_arrays())
        assert not clone.seeded and clone.n_seen == 100
        out = clone.push(recording[:, 100:])
        np.testing.assert_array_equal(out, _offline_std(recording))

    def test_short_stream_flush_matches_offline(self, recording):
        """A stream that ends before the seed block fills standardizes via
        flush() with the offline ``block = min(init_block, T)`` clause."""
        short = recording[:, :150]
        ems = StreamingEMS(C, init_block_size=BLOCK)
        assert ems.push(short).shape == (C, 0)
        out = ems.flush()
        np.testing.assert_array_equal(out, _offline_std(short))
        assert ems.flush().shape == (C, 0)  # idempotent

    def test_bad_inputs(self):
        ems = StreamingEMS(C)
        with pytest.raises(ValueError, match="chunk"):
            ems.push(np.zeros((C + 1, 10), np.float32))
        with pytest.raises(ValueError, match="chunk"):
            ems.push(np.zeros(10, np.float32))
        with pytest.raises(ValueError):
            StreamingEMS(0)


class TestStreamSession:
    def _decided(self, session, ready, pred=1):
        for idx, start, _ in ready:
            session.record(WindowDecision(index=idx, start=start, pred=pred,
                                          status="ok", latency_ms=1.0))

    def test_window_positions_match_offline_slicing(self, recording):
        session = StreamSession("s", n_channels=C, window=T, hop=HOP,
                                ems_init_block_size=BLOCK)
        ready = []
        for pos in range(0, recording.shape[1], 33):
            ready.extend(session.ingest(recording[:, pos:pos + 33]))
        offline = _offline_windows(_offline_std(recording))
        assert len(ready) == len(offline)
        for idx, start, win in ready:
            assert start == idx * HOP
            np.testing.assert_array_equal(win, offline[idx])

    def test_record_out_of_order_raises(self):
        session = StreamSession("s", n_channels=C, window=T, hop=HOP)
        with pytest.raises(ValueError, match="out of order"):
            session.record(WindowDecision(index=3, start=48, pred=0,
                                          status="ok", latency_ms=0.0))

    def test_decision_history_is_bounded(self, recording):
        """Review hardening: the durable decision record keeps only a
        bounded tail (cursors stay exact), so a multi-hour stream's
        periodic snapshots don't grow with stream age."""
        session = StreamSession("s", n_channels=C, window=T, hop=HOP,
                                ems_init_block_size=BLOCK,
                                decision_history=10)
        ready = session.ingest(recording[:, :1000])
        self._decided(session, ready)
        assert session.windows_decided == len(ready) > 10
        assert len(session.decisions) == 10
        assert session.preds_offset == len(ready) - 10
        assert session.decisions[0].index == session.preds_offset
        restored = StreamSession.from_state("s", session.state_arrays())
        assert restored.windows_decided == session.windows_decided
        assert restored.preds_offset == session.preds_offset
        np.testing.assert_array_equal(restored.preds(), session.preds())
        w1 = session.ingest(recording[:, 1000:])
        w2 = restored.ingest(recording[:, 1000:])
        assert len(w1) == len(w2) > 0
        for (i1, _, a), (i2, _, b) in zip(w1, w2):
            assert i1 == i2
            np.testing.assert_array_equal(a, b)

    def test_snapshot_rolls_back_to_decided_frontier(self, recording):
        """Windows produced but not yet decided when the state is captured
        are re-extracted byte-identically after restore — an in-flight
        window at crash time is re-decided, never lost."""
        session = StreamSession("s", n_channels=C, window=T, hop=HOP,
                                ems_init_block_size=BLOCK)
        ready = session.ingest(recording[:, :600])
        assert len(ready) > 4
        self._decided(session, ready[:3])  # 3 decided, rest in flight
        restored = StreamSession.from_state("s", session.state_arrays())
        assert restored.windows_decided == 3
        assert restored.acked == 600
        again = restored.ingest(np.zeros((C, 0), np.float32))
        assert [(i, s) for i, s, _ in again] \
            == [(i, s) for i, s, _ in ready[3:]]
        for (_, _, w1), (_, _, w2) in zip(ready[3:], again):
            np.testing.assert_array_equal(w1, w2)


class TestSessionStore:
    def _fill(self, store, x, sid="a", n=800):
        session, resumed = store.open(sid, n_channels=C, window=T, hop=HOP,
                                      ems_init_block_size=BLOCK)
        assert not resumed
        for idx, start, _ in session.ingest(x[:, :n]):
            session.record(WindowDecision(index=idx, start=start, pred=2,
                                          status="ok", latency_ms=1.0))
        return session

    def test_snapshot_restore_roundtrip(self, tmp_path, recording):
        store = SessionStore(tmp_path / "sessions.npz")
        session = self._fill(store, recording)
        store.snapshot()
        store.detach()

        store2 = SessionStore(tmp_path / "sessions.npz")
        assert store2.restore() == ["a"]
        restored = store2.get("a")
        assert restored.acked == session.acked
        assert restored.windows_decided == session.windows_decided
        np.testing.assert_array_equal(restored.preds(), session.preds())
        # The continued streams stay byte-identical.
        w1 = session.ingest(recording[:, 800:])
        w2 = restored.ingest(recording[:, 800:])
        assert len(w1) == len(w2) > 0
        for (_, _, a), (_, _, b) in zip(w1, w2):
            np.testing.assert_array_equal(a, b)
        store2.detach()

    def test_corrupt_newest_generation_falls_back(self, tmp_path,
                                                  recording):
        """Acceptance: a garbled newest snapshot is quarantined (journaled)
        and restore resumes from the previous valid generation."""
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            store = SessionStore(tmp_path / "sessions.npz", keep=2,
                                 journal=jr)
            session = self._fill(store, recording)
            store.snapshot()                     # valid fallback gen
            session.ingest(recording[:, 800:1000])
            with inject.scoped(inject.FaultSpec(site="session.snapshot",
                                                times=1)):
                store.snapshot()                 # garbled newest
            store.detach()
            store2 = SessionStore(tmp_path / "sessions.npz", journal=jr)
            assert store2.restore() == ["a"]
            assert store2.get("a").acked == 800  # gen1's state, not 1000
            store2.detach()
        events = schema.read_events(jr.events_path)
        kinds = {e["event"] for e in events}
        assert {"session_snapshot", "checkpoint_quarantine",
                "session_resume", "fault_injected"} <= kinds
        assert (tmp_path / "sessions.npz.corrupt").exists()
        resume = [e for e in events if e["event"] == "session_resume"][-1]
        assert resume["acked"] == 800

    def test_restore_missing_is_clean_start(self, tmp_path):
        store = SessionStore(tmp_path / "nope" / "sessions.npz")
        assert store.restore() == []
        store.detach()

    def test_close_is_durable(self, tmp_path, recording):
        store = SessionStore(tmp_path / "sessions.npz")
        self._fill(store, recording)
        store.close("a")  # snapshots the now-empty table
        store.detach()
        store2 = SessionStore(tmp_path / "sessions.npz")
        assert store2.restore() == []
        store2.detach()

    def test_reopen_reattaches(self, tmp_path, recording):
        store = SessionStore(tmp_path / "sessions.npz")
        self._fill(store, recording)
        session, resumed = store.open("a", n_channels=C, window=T, hop=HOP)
        assert resumed and session.acked == 800
        store.detach()

    def test_invalid_session_id_rejected(self, tmp_path):
        store = SessionStore(tmp_path / "sessions.npz")
        for bad in ("", "a/b", "x" * 65, "sp ace"):
            with pytest.raises(ValueError, match="session id"):
                store.open(bad, n_channels=C, window=T, hop=HOP)
        store.detach()

    def test_in_memory_store_has_no_snapshot(self, recording):
        store = SessionStore(None)
        self._fill(store, recording)
        assert store.snapshot() is None
        assert store.restore() == []
        store.detach()


class TestSpoolCompaction:
    """ISSUE-17 satellite: closed/migrated sessions are scrubbed from the
    retained snapshot generations, not just the newest one — otherwise a
    corrupt newest generation resurrects a departed stream on restore,
    and a cell-spool read fails a migrated session over to a second cell,
    forking the stream the migration just moved."""

    _fill = TestSessionStore._fill

    def _gens(self, path):
        gen_re = re.compile(re.escape(path.name) + r"\.gen\d+$")
        return [p for p in sorted(path.parent.glob(path.name + ".gen*"))
                if gen_re.fullmatch(p.name)]

    def test_close_scrubs_departed_from_every_generation(self, tmp_path,
                                                         recording):
        path = tmp_path / "sessions.npz"
        store = SessionStore(path, keep=4)
        self._fill(store, recording, sid="a")
        self._fill(store, recording, sid="b")
        store.snapshot()
        store.snapshot()  # rotate: retained gens now hold {a, b} too
        assert self._gens(path)
        store.close("a")
        for gen in self._gens(path):
            with np.load(gen, allow_pickle=False) as npz:
                assert not any(k.startswith("s/a/") for k in npz.files)
                meta = json.loads(bytes(npz["__meta__"]).decode())
            assert meta["sessions"] == ["b"]
        store.detach()
        # The co-resident open session's fallback state survived the
        # rewrite byte-for-byte usable: a restore still resumes it.
        store2 = SessionStore(path)
        assert store2.restore() == ["b"]
        assert store2.get("b").acked == 800
        store2.detach()

    def test_keep_guard_never_scrubs_an_open_session(self, tmp_path,
                                                     recording):
        path = tmp_path / "sessions.npz"
        store = SessionStore(path, keep=4)
        self._fill(store, recording, sid="a")
        store.snapshot()
        store.snapshot()
        assert store.compact_departed("a") == 0  # still open here
        for gen in self._gens(path):
            with np.load(gen, allow_pickle=False) as npz:
                assert any(k.startswith("s/a/") for k in npz.files)
        store.detach()

    def test_corrupt_newest_cannot_resurrect_closed_session(self, tmp_path,
                                                            recording):
        path = tmp_path / "sessions.npz"
        store = SessionStore(path, keep=4)
        self._fill(store, recording, sid="a")
        self._fill(store, recording, sid="b")
        store.snapshot()
        store.snapshot()
        store.close("a")
        store.detach()
        # Garble the newest snapshot: restore falls back to a retained
        # generation — which, compacted, no longer knows session "a".
        path.write_bytes(b"not a snapshot")
        store2 = SessionStore(path)
        assert store2.restore() == ["b"]
        store2.detach()

    def test_spool_read_misses_departed_session(self, tmp_path, recording):
        from eegnetreplication_tpu.serve.sessions.store import (
            read_spooled_session,
        )

        path = tmp_path / "spool" / "r0" / "sessions.npz"
        store = SessionStore(path, keep=4)
        self._fill(store, recording, sid="a")
        store.snapshot()
        store.snapshot()
        store.close("a")
        store.detach()
        assert read_spooled_session(tmp_path / "spool", "a") is None

    def test_generations_left_empty_are_unlinked(self, tmp_path,
                                                 recording):
        path = tmp_path / "sessions.npz"
        store = SessionStore(path, keep=4)
        self._fill(store, recording, sid="a")
        store.snapshot()
        store.snapshot()
        assert self._gens(path)
        store.close("a")
        assert self._gens(path) == []
        store.detach()


class TestSessionExportImport:
    """The ISSUE-12 migration wire format: single-session export/import
    under the full-store snapshot's integrity contract."""

    def _store_with_session(self, path, recording, sid="a"):
        store = SessionStore(path)
        session, _ = store.open(sid, n_channels=C, window=T, hop=HOP,
                                ems_init_block_size=BLOCK)
        for idx, start, _ in session.ingest(recording[:, :800]):
            session.record(WindowDecision(index=idx, start=start, pred=2,
                                          status="ok", latency_ms=1.0))
        return store, session

    def test_export_roundtrip_byte_parity_with_store_snapshot(
            self, tmp_path, recording):
        """An export IS a one-session store snapshot: same key layout,
        same content digest as snapshot() over a store holding only that
        session — not a second serialization format that could drift."""
        from eegnetreplication_tpu.resil import integrity
        from eegnetreplication_tpu.serve.sessions.store import (
            unpack_session,
        )

        store, session = self._store_with_session(
            tmp_path / "sessions.npz", recording)
        data = store.export_session("a")
        store.snapshot()
        store.detach()
        with np.load(tmp_path / "sessions.npz") as npz:
            full = {k: npz[k] for k in npz.files}
        import io as _io

        with np.load(_io.BytesIO(data)) as npz:
            exported = {k: npz[k] for k in npz.files}
        assert set(exported) == set(full)
        assert integrity.stored_digest(exported) \
            == integrity.stored_digest(full)
        for key in full:
            np.testing.assert_array_equal(exported[key], full[key])
        # And the import path rebuilds a byte-identical continued stream.
        sid, state = unpack_session(data)
        assert sid == "a"
        restored = StreamSession.from_state(sid, state)
        w1 = session.ingest(recording[:, 800:])
        w2 = restored.ingest(recording[:, 800:])
        assert len(w1) == len(w2) > 0
        for (_, _, a), (_, _, b) in zip(w1, w2):
            np.testing.assert_array_equal(a, b)

    def test_import_into_second_store_resumes_and_journals(
            self, tmp_path, recording):
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            store, session = self._store_with_session(
                tmp_path / "a" / "sessions.npz", recording)
            data = store.export_session("a")
            target = SessionStore(tmp_path / "b" / "sessions.npz",
                                  journal=jr)
            imported = target.import_session(data)
            assert imported.acked == session.acked
            assert imported.windows_decided == session.windows_decided
            np.testing.assert_array_equal(imported.preds(),
                                          session.preds())
            # The import persisted immediately: a restart of the target
            # resumes the migrated stream.
            target.detach()
            store.detach()
            reborn = SessionStore(tmp_path / "b" / "sessions.npz",
                                  journal=jr)
            assert reborn.restore() == ["a"]
            reborn.detach()
        resumes = [e for e in schema.read_events(jr.events_path)
                   if e["event"] == "session_resume"]
        assert resumes and resumes[0]["snapshot"] == "import"

    def test_tampered_import_refused_and_store_untouched(self, tmp_path,
                                                         recording):
        from eegnetreplication_tpu.resil.integrity import IntegrityError

        store, session = self._store_with_session(
            tmp_path / "sessions.npz", recording)
        data = store.export_session("a")
        before = session.acked
        # Flip one payload byte: the zip may still parse, the digest
        # must not — and a live session under the same id stays intact.
        for tampered in (data[: len(data) // 2],          # truncated
                         data[:-40] + b"\x00" * 40,       # garbled tail
                         b"not an npz at all"):
            with pytest.raises(IntegrityError):
                store.import_session(tampered)
        # Unstamped payloads are refused too (no legacy session exports
        # exist — absence of a digest IS tampering here).
        import io as _io

        with np.load(_io.BytesIO(data)) as npz:
            flat = {k: npz[k] for k in npz.files}
        from eegnetreplication_tpu.resil import integrity

        flat.pop(integrity.DIGEST_KEY)
        buf = _io.BytesIO()
        np.savez(buf, **flat)
        with pytest.raises(IntegrityError, match="no content digest"):
            store.import_session(buf.getvalue())
        assert store.get("a") is session and session.acked == before
        assert store.ids() == ["a"]
        store.detach()

    def test_import_of_open_id_rejected(self, tmp_path, recording):
        from eegnetreplication_tpu.serve.sessions.store import (
            SessionExists,
        )

        store, _ = self._store_with_session(tmp_path / "sessions.npz",
                                            recording)
        data = store.export_session("a")
        with pytest.raises(SessionExists):
            store.import_session(data)
        store.detach()

    def test_export_unknown_session_raises(self, tmp_path):
        store = SessionStore(tmp_path / "sessions.npz")
        with pytest.raises(KeyError):
            store.export_session("nope")
        store.detach()

    def test_peek_session_id(self, tmp_path, recording):
        # The fleet front peeks the id to keep imports sticky; the peek
        # must name the session without the full verify, and answer None
        # (never raise) for anything unreadable.
        from eegnetreplication_tpu.serve.sessions.store import (
            peek_session_id,
        )

        store, _ = self._store_with_session(tmp_path / "sessions.npz",
                                            recording)
        data = store.export_session("a")
        assert peek_session_id(data) == "a"
        assert peek_session_id(b"not an npz") is None
        assert peek_session_id(data[: len(data) // 4]) is None
        store.detach()

    def test_read_spooled_session_walks_generations(self, tmp_path,
                                                    recording):
        from eegnetreplication_tpu.serve.sessions.store import (
            read_spooled_session,
            unpack_session,
        )

        store, session = self._store_with_session(
            tmp_path / "spool" / "r0" / "sessions.npz", recording)
        store.snapshot()                        # the valid fallback gen
        session.ingest(recording[:, 800:1000])
        with inject.scoped(inject.FaultSpec(site="session.snapshot",
                                            times=1)):
            store.snapshot()                    # garbled newest gen
        store.detach()
        # Directory form (a cell's per-replica spool tree) resolves, and
        # the corrupt newest generation falls back to the valid one —
        # failover inherits the store's durability contract.
        data = read_spooled_session(tmp_path / "spool", "a")
        assert data is not None
        sid, state = unpack_session(data)
        assert sid == "a"
        assert StreamSession.from_state(sid, state).acked == 800
        assert read_spooled_session(tmp_path / "spool", "ghost") is None
        assert read_spooled_session(tmp_path / "empty", "a") is None


# ---------------------------------------------------------------------------
# HTTP surface.


def _checkpoint(tmp_path: Path) -> Path:
    model = EEGNet(n_channels=C, n_times=T)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, C, T)),
                           train=False)
    return save_checkpoint(
        tmp_path / "m.npz", variables["params"], variables["batch_stats"],
        metadata={"model": "eegnet", "n_channels": C, "n_times": T,
                  "F1": model.F1, "D": model.D})


def _post(url, data, ctype="application/json"):
    req = urllib.request.Request(url, data=data,
                                 headers={"Content-Type": ctype})
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read().decode())


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as resp:
        return json.loads(resp.read().decode())


class TestSessionHTTP:
    def test_full_roundtrip_matches_offline_pipeline(self, tmp_path,
                                                     recording):
        """Open -> raw-bytes samples -> state -> close; the decision
        stream must equal the offline pipeline (one-shot EMS, same
        windows, same engine) byte for byte."""
        ckpt = _checkpoint(tmp_path)
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            app = ServeApp(ckpt, buckets=(1, 8),
                           sessions_dir=tmp_path / "sess",
                           session_snapshot_every=16, journal=jr).start()
            try:
                opened = _post(app.url + "/session/open", json.dumps(
                    {"session": "s1", "hop": HOP,
                     "ems_init_block_size": BLOCK}).encode())
                assert opened["resumed"] is False
                assert opened["window"] == T
                for pos in range(0, recording.shape[1], 130):
                    chunk = recording[:, pos:pos + 130]
                    reply = _post(app.url + "/session/s1/samples",
                                  chunk.astype("<f4").tobytes(),
                                  "application/octet-stream")
                assert reply["acked"] == recording.shape[1]
                state = _get(app.url + "/session/s1/state")
                assert state["acked"] == recording.shape[1]
                assert state["seeded"] is True
                final = _post(app.url + "/session/s1/close", b"{}")
            finally:
                app.stop()
        engine = InferenceEngine.from_checkpoint(ckpt, (1, 8), warm=False)
        offline = engine.infer(_offline_windows(_offline_std(recording)))
        np.testing.assert_array_equal(
            np.asarray(final["preds"], np.int64), offline)
        assert final["windows"] == len(offline)
        assert final["expired"] == 0
        events = schema.read_events(jr.events_path)
        kinds = {e["event"] for e in events}
        assert {"session_start", "session_window", "session_snapshot",
                "session_end"} <= kinds
        summary = schema.event_summary(events)
        assert summary["n_sessions"] == 1
        assert summary["session_windows"] == len(offline)
        assert summary["windows_expired"] == 0
        assert summary["window_p95_ms"] > 0

    def test_json_samples_and_errors(self, tmp_path, recording):
        ckpt = _checkpoint(tmp_path)
        app = ServeApp(ckpt, buckets=(1, 8),
                       sessions_dir=tmp_path / "sess").start()
        try:
            _post(app.url + "/session/open",
                  json.dumps({"session": "j1", "hop": HOP}).encode())
            reply = _post(app.url + "/session/j1/samples", json.dumps(
                {"samples": recording[:, :50].tolist()}).encode())
            assert reply["acked"] == 50
            # Unknown session -> 404.
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(app.url + "/session/nope/samples", b"")
            assert err.value.code == 404
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(app.url + "/session/nope/state")
            assert err.value.code == 404
            # Ragged raw bytes -> 400.
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(app.url + "/session/j1/samples", b"\x00" * 7,
                      "application/octet-stream")
            assert err.value.code == 400
            # Session window must equal the model's input length.
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(app.url + "/session/open", json.dumps(
                    {"session": "j2", "window": T + 1}).encode())
            assert err.value.code == 400
            # Bad session id -> 400.
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(app.url + "/session/open", json.dumps(
                    {"session": "no/slash"}).encode())
            assert err.value.code == 400
            # A second close of the same session answers a clean 404
            # (the close claims the session atomically — racing closes
            # get one winner, never a KeyError 500).
            _post(app.url + "/session/j1/close", b"{}")
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(app.url + "/session/j1/close", b"{}")
            assert err.value.code == 404
        finally:
            app.stop()

    def test_export_import_discard_http_migration(self, tmp_path,
                                                  recording):
        """The migration wire protocol against real ServeApps: GET
        export -> POST import on the target (200; 409 on an open id;
        400 + untouched on tampered bytes) -> discard on the source —
        and the migrated stream continues byte-identically."""
        ckpt = _checkpoint(tmp_path)
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            source = ServeApp(ckpt, buckets=(1, 8),
                              sessions_dir=tmp_path / "src",
                              journal=jr).start()
            target = ServeApp(ckpt, buckets=(1, 8),
                              sessions_dir=tmp_path / "dst",
                              journal=jr).start()
            try:
                _post(source.url + "/session/open", json.dumps(
                    {"session": "m1", "hop": HOP,
                     "ems_init_block_size": BLOCK}).encode())
                half = recording[:, :1000]
                r1 = _post(source.url + "/session/m1/samples",
                           half.astype("<f4").tobytes(),
                           "application/octet-stream")
                req = urllib.request.Request(
                    source.url + "/session/m1/export")
                with urllib.request.urlopen(req, timeout=30) as resp:
                    data = resp.read()
                # Export of an unknown id is a 404.
                with pytest.raises(urllib.error.HTTPError) as err:
                    urllib.request.urlopen(urllib.request.Request(
                        source.url + "/session/zz/export"), timeout=30)
                assert err.value.code == 404
                # Tampered bytes: refused, target untouched.
                with pytest.raises(urllib.error.HTTPError) as err:
                    _post(target.url + "/session/import",
                          data[: len(data) // 2],
                          "application/octet-stream")
                assert err.value.code == 400
                assert "IntegrityError" in json.loads(
                    err.value.read().decode())["error"]
                imported = _post(target.url + "/session/import", data,
                                 "application/octet-stream")
                assert imported["imported"] and imported["acked"] == 1000
                # Importing over the now-open id answers 409.
                with pytest.raises(urllib.error.HTTPError) as err:
                    _post(target.url + "/session/import", data,
                          "application/octet-stream")
                assert err.value.code == 409
                # Source discards without deciding anything further.
                _post(source.url + "/session/m1/discard", b"{}")
                with pytest.raises(urllib.error.HTTPError) as err:
                    _get(source.url + "/session/m1/state")
                assert err.value.code == 404
                # The migrated stream continues on the target and the
                # stitched decisions equal the uninterrupted pipeline.
                _post(target.url + "/session/m1/samples",
                      recording[:, 1000:].astype("<f4").tobytes(),
                      "application/octet-stream")
                final = _post(target.url + "/session/m1/close", b"{}")
            finally:
                source.stop()
                target.stop()
        engine = InferenceEngine.from_checkpoint(ckpt, (1, 8), warm=False)
        offline = engine.infer(_offline_windows(_offline_std(recording)))
        np.testing.assert_array_equal(
            np.asarray(final["preds"], np.int64), offline)
        assert r1["acked"] == 1000
        events = schema.read_events(jr.events_path)
        resumes = [e for e in events if e["event"] == "session_resume"]
        assert resumes and resumes[-1]["snapshot"] == "import"
        ends = [e for e in events if e["event"] == "session_end"]
        assert any(e.get("reason") == "migrated" for e in ends)

    def test_expired_window_degrades_not_dies(self, tmp_path, recording):
        """A session whose per-window deadline cannot be met journals
        ``window_expired`` with ``pred=-1`` — and the stream KEEPS GOING:
        later ingests still ack and close still answers."""
        ckpt = _checkpoint(tmp_path)
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            app = ServeApp(ckpt, buckets=(1, 8),
                           sessions_dir=tmp_path / "sess",
                           journal=jr).start()
            try:
                _post(app.url + "/session/open", json.dumps(
                    {"session": "d1", "hop": HOP,
                     "ems_init_block_size": BLOCK,
                     "deadline_ms": 0.001}).encode())
                reply = _post(app.url + "/session/d1/samples",
                              recording[:, :600].astype("<f4").tobytes(),
                              "application/octet-stream")
                assert reply["acked"] == 600
                assert reply["decisions"]  # windows were decided...
                assert all(d["status"] == "expired" and d["pred"] == -1
                           for d in reply["decisions"])
                # ...and the stream is still alive:
                reply = _post(app.url + "/session/d1/samples",
                              recording[:, 600:700].astype("<f4").tobytes(),
                              "application/octet-stream")
                assert reply["acked"] == 700
                final = _post(app.url + "/session/d1/close", b"{}")
                assert final["expired"] == final["windows"] > 0
            finally:
                app.stop()
        events = schema.read_events(jr.events_path)
        expired = [e for e in events if e["event"] == "window_expired"]
        assert expired and expired[0]["session"] == "d1"
        summary = schema.event_summary(events)
        assert summary["windows_expired"] == summary["session_windows"] > 0

    def test_stop_snapshots_and_resume_continues_stream(self, tmp_path,
                                                        recording):
        """The serve drain persists sessions; a new ServeApp with
        ``resume=True`` restores them, the client resumes from the acked
        cursor, and the stitched decision stream equals the offline
        pipeline byte for byte."""
        ckpt = _checkpoint(tmp_path)
        sess_dir = tmp_path / "sess"
        cut = 1100
        with obs_journal.run(tmp_path / "obs1", config={}) as jr1:
            app = ServeApp(ckpt, buckets=(1, 8), sessions_dir=sess_dir,
                           journal=jr1).start()
            try:
                _post(app.url + "/session/open", json.dumps(
                    {"session": "r1", "hop": HOP,
                     "ems_init_block_size": BLOCK}).encode())
                _post(app.url + "/session/r1/samples",
                      recording[:, :cut].astype("<f4").tobytes(),
                      "application/octet-stream")
            finally:
                app.stop()  # SIGTERM-shaped drain: snapshot lands here
        with obs_journal.run(tmp_path / "obs2", config={}) as jr2:
            app2 = ServeApp(ckpt, buckets=(1, 8), sessions_dir=sess_dir,
                            resume=True, journal=jr2).start()
            try:
                state = _get(app2.url + "/session/r1/state")
                assert state["acked"] == cut
                # The re-open handshake reports resumed=True, cursor intact.
                reopened = _post(app2.url + "/session/open", json.dumps(
                    {"session": "r1", "hop": HOP}).encode())
                assert reopened["resumed"] is True
                assert reopened["acked"] == cut
                _post(app2.url + "/session/r1/samples",
                      recording[:, cut:].astype("<f4").tobytes(),
                      "application/octet-stream")
                final = _post(app2.url + "/session/r1/close", b"{}")
            finally:
                app2.stop()
        engine = InferenceEngine.from_checkpoint(ckpt, (1, 8), warm=False)
        offline = engine.infer(_offline_windows(_offline_std(recording)))
        np.testing.assert_array_equal(
            np.asarray(final["preds"], np.int64), offline)
        ev2 = schema.read_events(jr2.events_path)
        resumes = [e for e in ev2 if e["event"] == "session_resume"]
        assert len(resumes) == 1 and resumes[0]["acked"] == cut
        assert schema.event_summary(ev2)["session_resumes"] == 1


class TestLogFileDefault:
    """ISSUE-7 satellite: the log sink must not land as ``app.log`` in the
    CWD (repo pollution; supervisor children sharing a CWD collide)."""

    def test_default_under_reports_logs_with_pid(self, monkeypatch):
        from eegnetreplication_tpu.utils.logging import default_log_file

        monkeypatch.delenv("EEGTPU_LOG_FILE", raising=False)
        monkeypatch.setenv("EEGTPU_DATA_ROOT", "/some/root")
        path = Path(default_log_file())
        assert path.parent == Path("/some/root/reports/logs")
        assert path.name == f"app-{os.getpid()}.log"

    def test_explicit_override_wins(self, monkeypatch):
        from eegnetreplication_tpu.utils.logging import default_log_file

        monkeypatch.setenv("EEGTPU_LOG_FILE", "/tmp/custom.log")
        assert default_log_file() == "/tmp/custom.log"


class TestStreamBenchSelftest:
    def test_selftest_passes(self, tmp_path):
        """Tier-1 acceptance leg: paced 250 Hz replay with byte-identical
        decisions and p95 window latency under the hop interval, then
        SIGKILL-mid-stream under a supervisor with an exact resumed
        decision stream."""
        out = tmp_path / "BENCH_STREAM_selftest.json"
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "stream_bench.py"),
             "--selftest", "--seconds", "4", "--out", str(out)],
            capture_output=True, text=True, timeout=600,
            env=dict(os.environ, EEGTPU_NO_LOG_FILE="1",
                     EEGTPU_PLATFORM="cpu"))
        assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
        assert "SELFTEST PASS" in proc.stdout
        record = json.loads(out.read_text())
        replay = record["replay"]
        assert replay["parity"] is True
        assert replay["expired"] == 0
        assert replay["p95_window_ms"] < replay["hop_interval_ms"]
        resume = record["kill_resume"]
        assert resume["decisions_equal"] is True
        assert resume["duplicate_conflicts"] == 0
        assert resume["restarts"] >= 1
        assert resume["session_resumes"] >= 1
