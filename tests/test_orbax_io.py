"""Orbax checkpoint backend: roundtrip, resume, async, npz equivalence.

Mirrors the guarantees tests of the native ``.npz`` format
(``tests/test_checkpoint.py``) for the Orbax directory format that
multi-host deployments use (SURVEY.md §5: Orbax-style (params, opt_state,
step) checkpoints as the TPU equivalent of the reference's save-only
``torch.save``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("orbax.checkpoint")

from eegnetreplication_tpu.models import EEGNet  # noqa: E402
from eegnetreplication_tpu.training import checkpoint as ckpt
from eegnetreplication_tpu.training import orbax_io
from eegnetreplication_tpu.training.steps import (
    TrainState,
    make_optimizer,
    train_step,
)


@pytest.fixture
def small_net():
    model = EEGNet(n_channels=8, n_times=64)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 64)),
                           train=False)
    return model, variables


def _leaves_equal(a, b):
    la, lb = (jax.tree_util.tree_leaves(t) for t in (a, b))
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestOrbaxRoundtrip:
    def test_roundtrip_and_metadata(self, tmp_path, small_net):
        model, variables = small_net
        meta = {"model": "eegnet", "n_times": 64}  # Q4: T stays explicit
        p = orbax_io.save_orbax_checkpoint(
            tmp_path / "ck_orbax", variables["params"],
            variables["batch_stats"], meta)
        params, batch_stats, metadata = orbax_io.load_orbax_checkpoint(p)
        assert metadata == meta
        _leaves_equal(variables["params"], params)
        restored = {"params": params, "batch_stats": batch_stats}
        x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 64), jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(model.apply(variables, x, train=False)),
            np.asarray(model.apply(restored, x, train=False)))

    def test_restore_with_target_tree(self, tmp_path, small_net):
        _, variables = small_net
        p = orbax_io.save_orbax_checkpoint(
            tmp_path / "ck_target", variables["params"],
            variables["batch_stats"])
        target = {"params": variables["params"],
                  "batch_stats": variables["batch_stats"]}
        params, _, _ = orbax_io.load_orbax_checkpoint(p, target=target)
        _leaves_equal(variables["params"], params)

    def test_matches_npz_format(self, tmp_path, small_net):
        """Both formats must carry the identical state."""
        _, variables = small_net
        npz = ckpt.save_checkpoint(tmp_path / "ck.npz", variables["params"],
                                   variables["batch_stats"], {"m": 1})
        orb = orbax_io.save_orbax_checkpoint(
            tmp_path / "ck_orbax", variables["params"],
            variables["batch_stats"], {"m": 1})
        p_npz, bs_npz, meta_npz = ckpt.load_checkpoint(npz)
        p_orb, bs_orb, meta_orb = orbax_io.load_orbax_checkpoint(orb)
        assert meta_npz == meta_orb
        _leaves_equal(p_npz, p_orb)
        _leaves_equal(bs_npz, bs_orb)


class TestOrbaxResume:
    def test_train_state_resumes_identically(self, tmp_path, small_net):
        model, variables = small_net
        tx = make_optimizer()
        state = TrainState.create(variables, tx)
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(16, 8, 64), jnp.float32)
        y = jnp.asarray(rng.randint(0, 4, 16), jnp.int32)
        w = jnp.ones(16)
        for i in range(3):  # non-trivial Adam moments
            state, _ = train_step(model, tx, state, x, y, w,
                                  jax.random.PRNGKey(i))

        p = orbax_io.save_orbax_checkpoint(
            tmp_path / "resume_orbax", state.params, state.batch_stats,
            {"model": "eegnet"}, opt_state=state.opt_state, step=3)
        restored, step, meta = orbax_io.load_orbax_train_state(p, tx)
        assert step == 3 and meta["model"] == "eegnet"

        next_a, loss_a = train_step(model, tx, state, x, y, w,
                                    jax.random.PRNGKey(9))
        next_b, loss_b = train_step(model, tx, restored, x, y, w,
                                    jax.random.PRNGKey(9))
        assert float(loss_a) == float(loss_b)
        _leaves_equal(next_a.params, next_b.params)
        _leaves_equal(next_a.opt_state, next_b.opt_state)

    def test_weights_only_is_not_resumable(self, tmp_path, small_net):
        _, variables = small_net
        p = orbax_io.save_orbax_checkpoint(
            tmp_path / "wo_orbax", variables["params"],
            variables["batch_stats"])
        with pytest.raises(ValueError, match="not resumable"):
            orbax_io.load_orbax_train_state(p, make_optimizer())


class TestOrbaxAsync:
    def test_background_save_commits_after_wait(self, tmp_path, small_net):
        _, variables = small_net
        p = orbax_io.save_orbax_checkpoint(
            tmp_path / "async_orbax", variables["params"],
            variables["batch_stats"], {"bg": True}, background=True)
        orbax_io.wait_for_async_saves()
        params, _, meta = orbax_io.load_orbax_checkpoint(p)
        assert meta == {"bg": True}
        _leaves_equal(variables["params"], params)


class TestInterruptedSave:
    def test_missing_metadata_rejected_loudly(self, tmp_path, small_net):
        """A save that died between state commit and metadata write must not
        silently load with default model geometry."""
        _, variables = small_net
        p = orbax_io.save_orbax_checkpoint(
            tmp_path / "torn", variables["params"], variables["batch_stats"],
            {"n_times": 64})
        (p / "metadata.json").unlink()
        with pytest.raises(FileNotFoundError, match="interrupted"):
            orbax_io.load_orbax_checkpoint(p)
