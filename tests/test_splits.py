"""Split logic tests: sklearn parity and reference seeding semantics."""

import numpy as np
import pytest

from eegnetreplication_tpu.data.splits import (
    cross_subject_fold_subjects,
    inner_train_val_split,
    kfold_indices,
)


class TestKFold:
    def test_partition_properties(self):
        splits = kfold_indices(101, 4, seed=42)
        assert len(splits) == 4
        all_test = np.concatenate([t for _, t in splits])
        assert sorted(all_test) == list(range(101))
        for train, test in splits:
            assert len(np.intersect1d(train, test)) == 0
            assert len(train) + len(test) == 101

    def test_matches_sklearn(self):
        sklearn = pytest.importorskip("sklearn.model_selection")
        for n, k, seed in [(576, 4, 42), (101, 4, 42), (50, 5, 7)]:
            ours = kfold_indices(n, k, seed)
            theirs = list(
                sklearn.KFold(n_splits=k, shuffle=True,
                              random_state=seed).split(np.zeros(n)))
            for (otr, ote), (str_, ste) in zip(ours, theirs):
                np.testing.assert_array_equal(otr, str_)
                np.testing.assert_array_equal(ote, ste)

    def test_deterministic(self):
        a = kfold_indices(100, 4, seed=42)
        b = kfold_indices(100, 4, seed=42)
        for (atr, ate), (btr, bte) in zip(a, b):
            np.testing.assert_array_equal(atr, btr)
            np.testing.assert_array_equal(ate, bte)

    def test_too_many_splits_raises(self):
        with pytest.raises(ValueError):
            kfold_indices(3, 4)


class TestInnerSplit:
    def test_80_20_front_val(self):
        ids = np.arange(100, 200)
        train, val = inner_train_val_split(ids)
        # reference: val = first fifth, train = rest (train.py:77-79)
        np.testing.assert_array_equal(val, ids[:20])
        np.testing.assert_array_equal(train, ids[20:])


class TestCrossSubjectDraw:
    def test_excludes_test_subject_and_partitions(self):
        for subject in range(1, 10):
            tr, va = cross_subject_fold_subjects(subject, fold_count=1)
            assert subject not in tr and subject not in va
            assert len(tr) == 5 and len(va) == 3
            assert len(set(tr) | set(va)) == 8

    def test_matches_reference_seeding(self):
        """RandomState(42+fold_count).permutation over the ordered others."""
        subject, fold_count = 3, 17
        other = np.array([s for s in range(1, 10) if s != subject])
        expect = np.random.RandomState(42 + fold_count).permutation(other)
        tr, va = cross_subject_fold_subjects(subject, fold_count)
        np.testing.assert_array_equal(tr, expect[:5])
        np.testing.assert_array_equal(va, expect[5:])

    def test_folds_differ_across_repeats(self):
        draws = {tuple(cross_subject_fold_subjects(1, fc)[0]) for fc in range(1, 11)}
        assert len(draws) > 1

    def test_arbitrary_subject_labels(self):
        tr, va = cross_subject_fold_subjects(6, 1, subjects=(5, 6, 7, 8),
                                             n_train=2)
        assert 6 not in tr and 6 not in va
        assert set(tr) | set(va) == {5, 7, 8}
        assert len(tr) == 2 and len(va) == 1
