"""Resilience subsystem tests (``eegnetreplication_tpu/resil/``).

Covers the failure paths that were untestable before the fault-injection
registry existed: corrupt/truncated snapshots quarantined with fallback to
the previous generation, preemption → snapshot → preempted ``run_end`` →
successful ``--resume``, retry budget exhaustion surfacing the original
exception, and the staged fetch mirror never leaving a half-mirrored tree.
"""

import json
import os
import random
import subprocess
import sys
import threading
import time
import types
from pathlib import Path
from unittest import mock

import numpy as np
import pytest

from eegnetreplication_tpu import obs
from eegnetreplication_tpu.config import DEFAULT_TRAINING, Paths
from eegnetreplication_tpu.obs import schema
from eegnetreplication_tpu.resil import (
    breaker,
    heartbeat,
    inject,
    integrity,
    preempt,
    retry,
    supervise,
)
from eegnetreplication_tpu.training import checkpoint as ckpt
from eegnetreplication_tpu.training.protocols import within_subject_training
from synthetic import make_loader

REPO = Path(__file__).resolve().parent.parent
CFG = DEFAULT_TRAINING.replace(batch_size=16)

# Zero-delay policy so retry-path tests pay no wall for backoff.
FAST = retry.RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter=0.0)


class TestInjectRegistry:
    def test_unarmed_site_is_noop(self):
        inject.fire("data.read", path="x")  # nothing armed: no raise

    def test_after_times_counting_is_deterministic(self):
        handle = inject.arm("data.read", after=2, times=2)
        outcomes = []
        for _ in range(6):
            try:
                inject.fire("data.read")
                outcomes.append("ok")
            except OSError:
                outcomes.append("raised")
        assert outcomes == ["ok", "ok", "raised", "raised", "ok", "ok"]
        assert handle.hits == 6 and handle.fired == 2

    def test_times_zero_fires_every_hit(self):
        inject.arm("fetch.download", times=0)
        for _ in range(3):
            with pytest.raises(ConnectionError):
                inject.fire("fetch.download")

    def test_multi_spec_same_site_counting_stays_deterministic(self):
        # Both armed specs count every eligible hit even when the other
        # one fires on it: after=1 means "skip hit 1" regardless of what
        # the first spec did with that hit.
        inject.arm("checkpoint.write", action="raise", exc="OSError",
                   times=1)
        inject.arm("checkpoint.write", action="raise", exc="ValueError",
                   after=1, times=1)
        with pytest.raises(OSError):
            inject.fire("checkpoint.write")  # hit 1: spec A fires
        with pytest.raises(ValueError):
            inject.fire("checkpoint.write")  # hit 2: spec B (after=1) due
        inject.fire("checkpoint.write")  # both exhausted: no-op

    def test_if_folds_over_gates_eligibility(self):
        handle = inject.arm("train.step", if_folds_over=4, times=0)
        inject.fire("train.step", n_folds=3)  # too small: not eligible
        assert handle.hits == 0
        with pytest.raises(RuntimeError, match="UNAVAILABLE"):
            inject.fire("train.step", n_folds=8)
        assert retry.is_device_fault(_raises("train.step", n_folds=8))

    def test_scoped_disarms_even_when_fault_propagates(self):
        with pytest.raises(OSError):
            with inject.scoped(inject.FaultSpec(site="data.read")):
                inject.fire("data.read")
        assert inject.armed() == []
        inject.fire("data.read")  # disarmed: no raise

    def test_unknown_site_rejected_at_arm_time(self):
        with pytest.raises(ValueError, match="Unknown fault-injection site"):
            inject.arm("train.stpe")

    def test_corrupt_action_garbles_file(self, tmp_path):
        target = tmp_path / "blob.bin"
        target.write_bytes(b"A" * 100)
        inject.arm("checkpoint.write")
        inject.fire("checkpoint.write", path=target)
        assert target.read_bytes() != b"A" * 100

    def test_firing_is_journaled(self, tmp_path):
        with obs.run(tmp_path / "obs") as jr:
            inject.arm("data.read", times=1)
            with pytest.raises(OSError):
                inject.fire("data.read", path="/some/file")
        events = schema.read_events(jr.events_path)
        fired = [e for e in events if e["event"] == "fault_injected"]
        assert len(fired) == 1
        assert fired[0]["site"] == "data.read"
        assert fired[0]["action"] == "raise" and fired[0]["hit"] == 1
        assert not any("_schema_error" in e for e in events)

    def test_parse_plan_string(self):
        specs = inject.parse_plan(
            "train.step:if_folds_over=4:times=0,"
            "checkpoint.write:action=corrupt,host.preempt:after=2")
        assert [s.site for s in specs] == ["train.step", "checkpoint.write",
                                          "host.preempt"]
        assert specs[0].if_folds_over == 4 and specs[0].times == 0
        assert specs[1].action == "corrupt"
        assert specs[2].after == 2

    def test_parse_plan_file(self, tmp_path):
        plan = tmp_path / "plan.json"
        plan.write_text(json.dumps([{"site": "data.read", "times": 3}]))
        (spec,) = inject.parse_plan(f"@{plan}")
        assert spec.site == "data.read" and spec.times == 3

    def test_parse_plan_rejects_typos(self):
        with pytest.raises(ValueError, match="Unknown fault-injection site"):
            inject.parse_plan("train.stpe:times=1")
        with pytest.raises(ValueError, match="Unknown chaos plan option"):
            inject.parse_plan("train.step:tmies=1")
        # "site" is the positional head, not an option: must be the same
        # clean ValueError, not a TypeError the CLI handler misses.
        with pytest.raises(ValueError, match="Unknown chaos plan option"):
            inject.parse_plan("train.step:site=train.step")

    def test_parse_plan_file_rejects_bad_entries_as_valueerror(self, tmp_path):
        # The CLI catches ValueError for a clean parser.error; a plan-file
        # typo must not surface as FaultSpec's raw TypeError.
        plan = tmp_path / "plan.json"
        plan.write_text(json.dumps([{"site": "train.step", "tmies": 1}]))
        with pytest.raises(ValueError, match="Unknown chaos plan option"):
            inject.parse_plan(f"@{plan}")
        plan.write_text(json.dumps(["train.step"]))
        with pytest.raises(ValueError, match="must be objects"):
            inject.parse_plan(f"@{plan}")

    def test_parse_plan_file_rejects_non_string_fields(self, tmp_path):
        # A non-string message must fail at parse time, not as an
        # AttributeError when fire() formats it minutes into the run.
        plan = tmp_path / "plan.json"
        plan.write_text(json.dumps(
            [{"site": "train.chunk", "message": 5}]))
        with pytest.raises(ValueError, match="must be a string"):
            inject.parse_plan(f"@{plan}")

    def test_parse_plan_file_coerces_int_fields(self, tmp_path):
        plan = tmp_path / "plan.json"
        plan.write_text(json.dumps([{"site": "host.preempt", "after": "2"}]))
        (spec,) = inject.parse_plan(f"@{plan}")
        assert spec.after == 2
        plan.write_text(json.dumps([{"site": "host.preempt", "after": "x"}]))
        with pytest.raises(ValueError, match="must be an integer"):
            inject.parse_plan(f"@{plan}")


def _raises(site, **ctx):
    """fire() the armed site and hand back the exception it raised."""
    try:
        inject.fire(site, **ctx)
    except Exception as exc:  # noqa: BLE001 — the test inspects it
        return exc
    raise AssertionError(f"{site} did not fire")


class TestRetryPolicy:
    def test_classify(self):
        assert retry.classify(
            RuntimeError("UNAVAILABLE: TPU device error")) == "device_fault"
        assert retry.classify(ConnectionError("reset")) == "transient"
        assert retry.classify(TimeoutError()) == "transient"
        assert retry.classify(OSError("I/O error")) == "transient"
        assert retry.classify(FileNotFoundError("gone")) == "fatal"
        assert retry.classify(ValueError("bad")) == "fatal"
        assert retry.classify(RuntimeError("plain crash")) == "fatal"
        # Preempted must never be retried/halved: it is a graceful stop.
        assert retry.classify(preempt.Preempted("stop")) == "fatal"

    def test_is_device_fault_requires_runtimeerror(self):
        assert not retry.is_device_fault(OSError("UNAVAILABLE"))
        assert retry.is_device_fault(RuntimeError("DATA_LOSS on core 0"))

    def test_retries_then_succeeds(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("reset")
            return 42

        assert retry.call(flaky, policy=FAST, site="test") == 42
        assert len(calls) == 3

    def test_budget_exhaustion_surfaces_original_exception(self):
        sentinel = ConnectionError("the root cause")

        def always_fails():
            raise sentinel

        with pytest.raises(ConnectionError) as ei:
            retry.call(always_fails, policy=FAST, site="test")
        assert ei.value is sentinel  # the ORIGINAL instance, not a wrapper

    def test_fatal_classification_not_retried(self):
        calls = []

        def fatal():
            calls.append(1)
            raise ValueError("deterministic")

        with pytest.raises(ValueError):
            retry.call(fatal, policy=FAST, site="test")
        assert len(calls) == 1

    def test_deadline_budget(self):
        calls = []

        def flaky():
            calls.append(1)
            raise ConnectionError("reset")

        policy = retry.RetryPolicy(max_attempts=100, base_delay_s=0.0,
                                   jitter=0.0, deadline_s=0.0)
        with pytest.raises(ConnectionError):
            retry.call(flaky, policy=policy, site="test")
        assert len(calls) == 1  # deadline already spent after attempt 1

    def test_backoff_curve_and_cap(self):
        policy = retry.RetryPolicy(base_delay_s=1.0, multiplier=2.0,
                                   max_delay_s=5.0, jitter=0.0)
        assert [policy.delay(a) for a in (1, 2, 3, 4)] == [1.0, 2.0, 4.0, 5.0]

    def test_retries_are_journaled(self, tmp_path):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 2:
                raise ConnectionError("reset")
            return "ok"

        with obs.run(tmp_path / "obs") as jr:
            retry.call(flaky, policy=FAST, site="fetch.download")
        events = schema.read_events(jr.events_path)
        retries = [e for e in events if e["event"] == "retry"]
        assert len(retries) == 1
        assert retries[0]["site"] == "fetch.download"
        assert retries[0]["attempt"] == 1
        assert retries[0]["classification"] == "transient"
        assert "ConnectionError" in retries[0]["error"]


class TestIntegrity:
    def _flat(self):
        return {"params/w": np.arange(6, dtype=np.float32).reshape(2, 3),
                "params/b": np.zeros(3, dtype=np.float32)}

    def test_stamp_verify_roundtrip(self):
        flat = integrity.stamp(self._flat())
        integrity.verify(flat)  # no raise

    def test_tampered_payload_detected(self):
        flat = integrity.stamp(self._flat())
        flat["params/w"] = flat["params/w"] + 1
        with pytest.raises(integrity.IntegrityError, match="digest mismatch"):
            integrity.verify(flat)

    def test_signature_rewrite_does_not_invalidate(self):
        # __signature__ is excluded: resume logic validates it semantically,
        # and migration tooling legitimately rewrites it in place.
        flat = self._flat()
        flat["__signature__"] = np.frombuffer(b'{"a":1}', dtype=np.uint8)
        integrity.stamp(flat)
        flat["__signature__"] = np.frombuffer(b'{"a":2}', dtype=np.uint8)
        integrity.verify(flat)

    def test_legacy_digestless_passes(self):
        integrity.verify(self._flat())  # no digest entry: not corruption


class TestCheckpointIntegrity:
    def test_tampered_checkpoint_quarantined_on_load(self, tmp_path):
        p = ckpt.save_checkpoint(
            tmp_path / "ck.npz", {"w": np.ones((2, 2), np.float32)},
            {"mean": np.zeros(2, np.float32)}, {"model": "t"})
        with np.load(p, allow_pickle=False) as data:
            flat = {k: data[k] for k in data.files}
        flat["params/w"] = flat["params/w"] + 1  # damaged weights
        with open(p, "wb") as fh:
            np.savez(fh, **flat)
        with pytest.raises(integrity.IntegrityError):
            ckpt.load_checkpoint(p)
        assert not p.exists()  # moved aside, not left in place
        assert p.with_name(p.name + ".corrupt").exists()

    def _snap(self, path, epochs_done, fill, **kw):
        carry = {"w": np.full((2, 3), fill, np.float32)}
        return ckpt.save_run_snapshot(path, carry, {"loss": np.ones(2)},
                                      epochs_done, {"run": "t"}, **kw)

    def test_rotation_keeps_n_generations(self, tmp_path):
        p = tmp_path / "snap.npz"
        for n in (1, 2, 3):
            self._snap(p, epochs_done=n, fill=float(n), keep=2)
        gen1 = p.with_name(p.name + ".gen1")
        assert p.exists() and gen1.exists()
        assert not p.with_name(p.name + ".gen2").exists()
        template = {"w": np.zeros((2, 3), np.float32)}
        _, _, newest = ckpt.load_run_snapshot(p, template, {"run": "t"})
        assert newest == 3

    def test_corrupt_newest_falls_back_to_previous_generation(self, tmp_path):
        p = tmp_path / "snap.npz"
        self._snap(p, epochs_done=2, fill=2.0, keep=2)
        self._snap(p, epochs_done=4, fill=4.0, keep=2)
        p.write_bytes(p.read_bytes()[: p.stat().st_size // 2])  # truncated
        template = {"w": np.zeros((2, 3), np.float32)}
        with obs.run(tmp_path / "obs") as jr:
            carry, _, epochs_done = ckpt.load_run_snapshot(p, template,
                                                           {"run": "t"})
        assert epochs_done == 2  # the previous generation answered
        np.testing.assert_array_equal(carry["w"],
                                      np.full((2, 3), 2.0, np.float32))
        assert p.with_name(p.name + ".corrupt").exists()
        events = schema.read_events(jr.events_path)
        quarantines = [e for e in events
                       if e["event"] == "checkpoint_quarantine"]
        assert len(quarantines) == 1

    def test_quarantine_hole_does_not_strand_older_generation(self, tmp_path,
                                                              monkeypatch):
        # keep=3: newest and gen1 corrupt, gen2 valid.  The signature read
        # quarantines the two corpses (leaving holes in the .genN chain);
        # the subsequent full load must still resolve gen2 — the chain walk
        # may not stop at a hole.
        monkeypatch.setenv("EEGTPU_SNAPSHOT_KEEP", "3")
        p = tmp_path / "snap.npz"
        for n in (2, 4, 6):
            self._snap(p, epochs_done=n, fill=float(n), keep=3)
        p.write_bytes(b"junk")
        p.with_name(p.name + ".gen1").write_bytes(b"junk")
        assert ckpt.read_snapshot_signature(p) == {"run": "t"}
        template = {"w": np.zeros((2, 3), np.float32)}
        carry, _, epochs_done = ckpt.load_run_snapshot(p, template,
                                                       {"run": "t"})
        assert epochs_done == 2  # gen2 (the oldest) survived and answered
        np.testing.assert_array_equal(carry["w"],
                                      np.full((2, 3), 2.0, np.float32))

    def test_all_generations_corrupt_raises_filenotfound(self, tmp_path):
        p = tmp_path / "snap.npz"
        self._snap(p, epochs_done=2, fill=2.0, keep=2)
        self._snap(p, epochs_done=4, fill=4.0, keep=2)
        p.write_bytes(b"junk")
        p.with_name(p.name + ".gen1").write_bytes(b"junk")
        with pytest.raises(FileNotFoundError, match="all generations"):
            ckpt.load_run_snapshot(p, {"w": np.zeros((2, 3), np.float32)},
                                   {"run": "t"})

    def test_missing_primary_resolves_gen1(self, tmp_path):
        # The crash window between rotation and the new write landing:
        # primary gone, gen1 holds the previous valid snapshot.
        p = tmp_path / "snap.npz"
        self._snap(p, epochs_done=2, fill=2.0, keep=2)
        self._snap(p, epochs_done=4, fill=4.0, keep=2)
        p.unlink()
        assert ckpt.any_snapshot_generation(p)
        assert ckpt.read_snapshot_signature(p) == {"run": "t"}
        template = {"w": np.zeros((2, 3), np.float32)}
        _, _, epochs_done = ckpt.load_run_snapshot(p, template, {"run": "t"})
        assert epochs_done == 2
        assert not ckpt.any_snapshot_generation(tmp_path / "nothing.npz")

    def test_repeated_loads_stable(self, tmp_path):
        # The resolve memo (signature read -> load fast path) must not
        # hand a second load a hollowed-out dict.
        p = tmp_path / "snap.npz"
        self._snap(p, epochs_done=3, fill=3.0, keep=2)
        template = {"w": np.zeros((2, 3), np.float32)}
        for _ in range(2):
            carry, _, epochs_done = ckpt.load_run_snapshot(p, template,
                                                           {"run": "t"})
            assert epochs_done == 3
            np.testing.assert_array_equal(
                carry["w"], np.full((2, 3), 3.0, np.float32))

    def test_unreadable_checkpoint_raises_integrity_error(self, tmp_path):
        # Corruption that breaks the zip container itself (the usual
        # crash-mid-write shape) must surface as IntegrityError, not leak
        # a raw BadZipFile — but WITHOUT quarantining: an unreadable file
        # cannot be proven framework-owned, and predict/viz hand these
        # loaders arbitrary user paths that must not be renamed away.
        p = ckpt.save_checkpoint(
            tmp_path / "ck.npz", {"w": np.ones((2, 2), np.float32)},
            {"mean": np.zeros(2, np.float32)}, {"model": "t"})
        p.write_bytes(p.read_bytes()[: p.stat().st_size // 2])
        with pytest.raises(integrity.IntegrityError, match="unreadable"):
            ckpt.load_checkpoint(p)
        assert p.exists()  # the user's file stays in place

    def test_resolve_memo_reused_and_released(self, tmp_path, monkeypatch):
        # The grouped resume flow probes the signature twice before the
        # full load: the decompress+sha256 walk must hit disk once for all
        # three resolves, and the terminal load must release the memo so
        # the snapshot's arrays are not pinned for the rest of the run.
        p = tmp_path / "snap.npz"
        self._snap(p, epochs_done=3, fill=3.0, keep=2)
        reads = []
        real = ckpt._read_flat
        monkeypatch.setattr(ckpt, "_read_flat",
                            lambda path: reads.append(path) or real(path))
        assert ckpt.read_snapshot_signature(p) == {"run": "t"}
        assert ckpt.read_snapshot_signature(p) == {"run": "t"}
        template = {"w": np.zeros((2, 3), np.float32)}
        _, _, epochs_done = ckpt.load_run_snapshot(p, template, {"run": "t"})
        assert epochs_done == 3
        assert len(reads) == 1
        assert not ckpt._RESOLVE_MEMO

    def test_armed_checkpoint_write_caught_by_loader(self, tmp_path):
        # The chaos site garbles the STAGED bytes (crash-mid-replace shape);
        # the loader must refuse the landed file.
        inject.arm("checkpoint.write", times=1)
        p = ckpt.save_checkpoint(
            tmp_path / "ck.npz", {"w": np.ones((4, 4), np.float32)},
            {"m": np.zeros(4, np.float32)}, {})
        with pytest.raises(Exception):  # zip damage or digest mismatch
            ckpt.load_checkpoint(p)

    def test_snapshot_keep_env_knob(self, monkeypatch):
        monkeypatch.setenv("EEGTPU_SNAPSHOT_KEEP", "5")
        assert ckpt.snapshot_keep() == 5
        monkeypatch.setenv("EEGTPU_SNAPSHOT_KEEP", "0")
        assert ckpt.snapshot_keep() == 1  # clamped: newest always kept
        monkeypatch.setenv("EEGTPU_SNAPSHOT_KEEP", "bogus")
        assert ckpt.snapshot_keep() == ckpt.DEFAULT_SNAPSHOT_KEEP


class TestProtocolResilience:
    """End-to-end recovery through the protocol layer (synthetic data)."""

    def _run(self, tmp_paths, **kw):
        loader = make_loader(n_trials=24, n_channels=4, n_times=64)
        return within_subject_training(
            epochs=6, config=CFG, loader=loader, subjects=(1,),
            paths=tmp_paths, seed=0, save_models=False, **kw)

    @pytest.fixture
    def tmp_paths(self, tmp_path):
        return Paths.from_root(tmp_path)

    def test_corrupt_snapshot_falls_back_to_previous_generation(
            self, tmp_paths, caplog):
        import logging

        uninterrupted = self._run(tmp_paths, checkpoint_every=2)
        # Crash after the SECOND chunk: snapshots for epochs 2 (gen1) and 4
        # (newest) both exist.
        with pytest.raises(RuntimeError, match="injected crash"):
            self._run(tmp_paths, checkpoint_every=2, _crash_after_chunk=2)
        snap = tmp_paths.models / "within_subject_eegnet.run.npz"
        gen1 = snap.with_name(snap.name + ".gen1")
        assert snap.exists() and gen1.exists()
        # The newest generation is truncated (crash mid-replace shape).
        snap.write_bytes(snap.read_bytes()[: snap.stat().st_size // 2])
        with caplog.at_level(logging.WARNING):
            resumed = self._run(tmp_paths, checkpoint_every=2, resume=True)
        assert any("falling back to previous generation" in r.getMessage()
                   for r in caplog.records)
        np.testing.assert_array_equal(resumed.fold_test_acc,
                                      uninterrupted.fold_test_acc)
        # Completion cleans up snapshot, generations, and corpses alike.
        assert not snap.exists() and not gen1.exists()
        assert not list(tmp_paths.models.glob("*.corrupt"))

    def test_preempt_snapshots_and_resumes(self, tmp_paths, tmp_path):
        uninterrupted = self._run(tmp_paths, checkpoint_every=2)
        with obs.run(tmp_path / "obs") as jr:
            try:
                with inject.scoped(
                        inject.FaultSpec(site="host.preempt", times=1)):
                    with pytest.raises(preempt.Preempted):
                        self._run(tmp_paths, checkpoint_every=2)
            finally:
                # What train.py's entrypoint does on Preempted.
                jr.run_end(status="preempted", error="preempted in test")
        events = schema.read_events(jr.events_path)
        assert events[-1]["event"] == "run_end"
        assert events[-1]["status"] == "preempted"
        assert any(e["event"] == "fault_injected"
                   and e["site"] == "host.preempt" for e in events)
        snap = tmp_paths.models / "within_subject_eegnet.run.npz"
        assert snap.exists()  # the stop happened AFTER the snapshot landed
        preempt.clear()  # a real rerun is a fresh process
        resumed = self._run(tmp_paths, checkpoint_every=2, resume=True)
        np.testing.assert_array_equal(resumed.fold_test_acc,
                                      uninterrupted.fold_test_acc)
        assert not snap.exists()

    def test_sigterm_style_request_honored_at_chunk_boundary(self, tmp_paths):
        # Request the stop BEFORE training: the first snapshot boundary
        # must honor it (the signal handler path sets the same flag).
        preempt.request("test-SIGTERM")
        with pytest.raises(preempt.Preempted, match="--resume"):
            self._run(tmp_paths, checkpoint_every=2)
        assert (tmp_paths.models / "within_subject_eegnet.run.npz").exists()

    def test_registry_armed_device_fault_halves_and_journals(
            self, tmp_paths, tmp_path, monkeypatch):
        from eegnetreplication_tpu.training import protocols as P

        monkeypatch.setattr(P, "_fold_batch_limit_path",
                            lambda: tmp_path / "limits.json")
        with obs.run(tmp_path / "obs") as jr:
            with inject.scoped(inject.FaultSpec(site="train.step", times=0,
                                                if_folds_over=2)):
                result = self._run(tmp_paths, fold_batch=3)
        assert len(result.per_subject_test_acc) == 1
        events = schema.read_events(jr.events_path)
        kinds = [e["event"] for e in events]
        assert "fault_injected" in kinds  # the armed site fired
        assert "device_fault" in kinds    # the halving loop classified it
        assert "retry" in kinds           # ...and journaled the shared record
        assert result.fault_retry_wall_s >= 0.0

    def test_shim_kwargs_leave_registry_clean(self, tmp_paths):
        with pytest.raises(RuntimeError, match="injected crash"):
            self._run(tmp_paths, checkpoint_every=2, _crash_after_chunk=1)
        assert inject.armed() == []  # the shim's scoped arm was released


class TestFetchResilience:
    def _install_kagglehub(self, cache: Path, calls: list):
        mod = types.ModuleType("kagglehub")

        def dataset_download(dataset):
            calls.append(dataset)
            return str(cache)

        mod.dataset_download = dataset_download
        return mock.patch.dict(sys.modules, {"kagglehub": mod})

    def test_download_retries_injected_fault(self, tmp_path, monkeypatch):
        import eegnetreplication_tpu.fetch as fetch

        monkeypatch.setattr(fetch, "DOWNLOAD_RETRY", FAST)
        cache = tmp_path / "cache"
        cache.mkdir()
        (cache / "A01T.gdf").write_bytes(b"gdf")
        paths = Paths.from_root(tmp_path / "proj")
        calls: list = []
        inject.arm("fetch.download", times=2)
        with self._install_kagglehub(cache, calls), \
                obs.run(tmp_path / "obs") as jr:
            out = fetch.fetch_from_kaggle(paths=paths)
        assert calls == [fetch.KAGGLE_DATASET]  # 2 injected, 3rd real
        assert (out / "A01T.gdf").read_bytes() == b"gdf"
        events = schema.read_events(jr.events_path)
        assert sum(e["event"] == "retry" for e in events) == 2
        assert sum(e["event"] == "fault_injected" for e in events) == 2

    def test_download_budget_exhaustion_surfaces_original(self, tmp_path,
                                                          monkeypatch):
        import eegnetreplication_tpu.fetch as fetch

        monkeypatch.setattr(fetch, "DOWNLOAD_RETRY", FAST)
        paths = Paths.from_root(tmp_path / "proj")
        inject.arm("fetch.download", times=0)  # never stops failing
        with self._install_kagglehub(tmp_path, []):
            with pytest.raises(ConnectionError,
                               match="injected fault: fetch.download"):
                fetch.fetch_from_kaggle(paths=paths)
        assert not paths.data_raw.exists()  # nothing half-mirrored

    def test_interrupted_mirror_leaves_dest_intact(self, tmp_path):
        from eegnetreplication_tpu.fetch import _mirror_into

        cache = tmp_path / "cache"
        cache.mkdir()
        (cache / "a.gdf").write_bytes(b"new-a")
        (cache / "b.gdf").write_bytes(b"new-b")
        dest = tmp_path / "data_raw"
        dest.mkdir()
        (dest / "a.gdf").write_bytes(b"old-a")

        import shutil as shutil_mod
        real_copy2 = shutil_mod.copy2

        def failing_copy2(src, dst, **kw):
            if str(src).startswith(str(cache)):
                raise OSError("disk full mid-copy")
            return real_copy2(src, dst, **kw)

        with mock.patch.object(shutil_mod, "copy2", failing_copy2):
            with pytest.raises(OSError, match="disk full"):
                _mirror_into(cache, dest)
        # The interrupted fetch changed NOTHING: old content intact, no
        # partial new files, no staging litter.
        assert sorted(p.name for p in dest.iterdir()) == ["a.gdf"]
        assert (dest / "a.gdf").read_bytes() == b"old-a"
        assert not list(tmp_path.glob(".data_raw.staging*"))

    def test_mirror_swap_replaces_stale_entries(self, tmp_path):
        from eegnetreplication_tpu.fetch import _mirror_into

        cache = tmp_path / "cache"
        (cache / "Train").mkdir(parents=True)
        (cache / "Train" / "fresh.gdf").write_bytes(b"fresh")
        dest = tmp_path / "data_raw"
        (dest / "Train").mkdir(parents=True)
        (dest / "Train" / "orphan.gdf").write_bytes(b"old")
        (dest / "keep.txt").write_bytes(b"keep")  # not in cache: preserved
        keep_ino = (dest / "keep.txt").stat().st_ino
        _mirror_into(cache, dest)
        assert (dest / "Train" / "fresh.gdf").read_bytes() == b"fresh"
        assert not (dest / "Train" / "orphan.gdf").exists()
        assert (dest / "keep.txt").read_bytes() == b"keep"
        # Preserved entries ride through by hardlink, not a byte copy.
        assert (dest / "keep.txt").stat().st_ino == keep_ino

    def test_mirror_restores_dest_when_swap_fails(self, tmp_path):
        from eegnetreplication_tpu.fetch import _mirror_into

        cache = tmp_path / "cache"
        cache.mkdir()
        (cache / "a.gdf").write_bytes(b"new-a")
        dest = tmp_path / "data_raw"
        dest.mkdir()
        (dest / "a.gdf").write_bytes(b"old-a")

        real_replace = Path.replace

        def failing_replace(self, target):
            if ".staging" in self.name:  # the staging -> dest rename only
                raise OSError("simulated rename failure")
            return real_replace(self, target)

        with mock.patch.object(Path, "replace", failing_replace):
            with pytest.raises(OSError, match="simulated rename"):
                _mirror_into(cache, dest)
        # dest was already retired when the swap failed: the old complete
        # tree must come back, not sit stranded in a hidden .old dir.
        assert (dest / "a.gdf").read_bytes() == b"old-a"
        assert not list(tmp_path.glob(".data_raw.old*"))
        assert not list(tmp_path.glob(".data_raw.staging*"))

    def test_mirror_recovers_leftovers_from_crashed_prior_run(self, tmp_path):
        import subprocess

        from eegnetreplication_tpu.fetch import _mirror_into

        # A genuinely dead pid: a reaped child (immediate reuse of a just
        # freed pid is effectively impossible).
        child = subprocess.Popen(["true"])
        child.wait()
        dead_pid = child.pid

        cache = tmp_path / "cache"
        cache.mkdir()
        (cache / "a.gdf").write_bytes(b"new-a")
        # A prior fetch (the dead pid) was SIGKILLed inside the rename
        # window: dest is gone, its complete old tree sits retired, and an
        # orphaned staging tree litters the parent.
        retired = tmp_path / f".data_raw.old.{dead_pid}"
        retired.mkdir()
        (retired / "prev.gdf").write_bytes(b"prev")
        orphan = tmp_path / f".data_raw.staging.{dead_pid}"
        orphan.mkdir()
        (orphan / "half.gdf").write_bytes(b"half")
        dest = tmp_path / "data_raw"
        _mirror_into(cache, dest)
        # The retired tree came back as dest (prev.gdf preserved) before
        # the cache was overlaid, and no orphaned litter survives.
        assert (dest / "prev.gdf").read_bytes() == b"prev"
        assert (dest / "a.gdf").read_bytes() == b"new-a"
        assert not list(tmp_path.glob(".data_raw.*"))

    def test_mirror_preserves_concurrent_fetch_trees(self, tmp_path):
        from eegnetreplication_tpu import fetch as fetch_mod

        cache = tmp_path / "cache"
        cache.mkdir()
        (cache / "a.gdf").write_bytes(b"new-a")
        # Another fetch (live owner) is mid-swap on the SAME dest: its
        # retired tree is its rollback copy and must survive our cleanup.
        live_retired = tmp_path / ".data_raw.old.424242"
        live_retired.mkdir()
        (live_retired / "rollback.gdf").write_bytes(b"rb")
        dest = tmp_path / "data_raw"
        dest.mkdir()
        (dest / "a.gdf").write_bytes(b"old-a")
        with mock.patch.object(fetch_mod, "_pid_alive", lambda pid: True):
            fetch_mod._mirror_into(cache, dest)
        assert (dest / "a.gdf").read_bytes() == b"new-a"
        assert (live_retired / "rollback.gdf").read_bytes() == b"rb"

    def test_data_read_retries_injected_fault(self, tmp_path, monkeypatch):
        from eegnetreplication_tpu.data import io as data_io
        from eegnetreplication_tpu.data.containers import BCICI2ADataset

        monkeypatch.setattr(data_io, "READ_RETRY", FAST)
        ds = BCICI2ADataset(X=np.zeros((4, 2, 8), np.float32),
                            y=np.zeros(4, np.int64))
        p = data_io.save_trials(ds, tmp_path / "t.npz")
        inject.arm("data.read", times=1)
        loaded = data_io.load_trials(p)
        assert loaded.X.shape == (4, 2, 8)


class TestHeartbeat:
    def test_beat_write_read_roundtrip(self, tmp_path):
        hb = heartbeat.Heartbeat(tmp_path / "hb.json",
                                 min_write_interval_s=0.0)
        sent = hb.beat("step")
        got = heartbeat.read(tmp_path / "hb.json")
        assert got == sent
        assert got.phase == "step" and got.pid == os.getpid()

    def test_write_throttle_but_phase_change_writes(self, tmp_path):
        hb = heartbeat.Heartbeat(tmp_path / "hb.json",
                                 min_write_interval_s=60.0)
        hb.beat("step")
        hb.beat("step")  # throttled: same phase inside the interval
        assert heartbeat.read(tmp_path / "hb.json").beat == 1
        hb.beat("serve_forward")  # phase change must land immediately
        assert heartbeat.read(tmp_path / "hb.json").phase == "serve_forward"

    def test_unreadable_file_reads_as_none(self, tmp_path):
        assert heartbeat.read(tmp_path / "missing.json") is None
        (tmp_path / "torn.json").write_text('{"phase": "st')
        assert heartbeat.read(tmp_path / "torn.json") is None

    def test_journal_throttle(self, tmp_path):
        with obs.run(tmp_path / "obs") as jr:
            hb = heartbeat.Heartbeat(journal_every_s=3600.0)
            for _ in range(5):
                hb.beat("step")
        events = schema.read_events(jr.events_path)
        beats = [e for e in events if e["event"] == "heartbeat"]
        assert len(beats) == 1  # first beat journaled, rest throttled
        assert beats[0]["phase"] == "step"
        assert not any("_schema_error" in e for e in events)

    def test_default_emitter_configured_from_env(self, tmp_path,
                                                 monkeypatch):
        monkeypatch.setenv(heartbeat.HEARTBEAT_FILE_ENV,
                           str(tmp_path / "env_hb.json"))
        heartbeat.reset_default()
        heartbeat.beat("fetch")
        assert heartbeat.read(tmp_path / "env_hb.json").phase == "fetch"

    def test_watchdog_per_phase_thresholds(self):
        wd = heartbeat.Watchdog({"step": 0.1, "compile": 100.0})
        old = heartbeat.Beat(phase="step", beat=1, t=time.time() - 1.0,
                             pid=1)
        assert wd.check_beat(old).stale
        compiling = heartbeat.Beat(phase="compile", beat=1,
                                   t=time.time() - 1.0, pid=1)
        v = wd.check_beat(compiling)
        assert not v.stale and v.threshold_s == 100.0

    def test_watchdog_missing_beat_uses_startup_budget(self):
        wd = heartbeat.Watchdog({"startup": 0.5})
        assert not wd.check_beat(None).stale  # nothing to age against
        v = wd.check_beat(None, since=time.time() - 1.0)
        assert v.stale and v.phase == "startup"

    def test_watchdog_pid_gate_ignores_foreign_beats(self, tmp_path):
        hb = heartbeat.Heartbeat(tmp_path / "hb.json",
                                 min_write_interval_s=0.0)
        hb.beat("step")
        wd = heartbeat.Watchdog({"startup": 0.1})
        # A beat from another pid must not vouch for this child.
        v = wd.check_file(tmp_path / "hb.json", pid=os.getpid() + 1,
                          since=time.time() - 1.0)
        assert v.stale and v.phase == "startup"
        assert not wd.check_file(tmp_path / "hb.json",
                                 pid=os.getpid()).stale


class TestCircuitBreakerUnit:
    def _clocked(self, **kw):
        now = [0.0]
        b = breaker.CircuitBreaker(clock=lambda: now[0], **kw)
        return b, now

    def test_opens_after_consecutive_failures_only(self):
        b, _ = self._clocked(failure_threshold=3)
        b.record_failure()
        b.record_failure()
        b.record_success()  # resets the consecutive count
        b.record_failure()
        b.record_failure()
        assert b.state == "closed"
        b.record_failure()
        assert b.state == "open" and not b.allow()
        assert b.trips == 1

    def test_half_open_probe_closes_or_reopens(self):
        b, now = self._clocked(failure_threshold=1, reset_after_s=10.0)
        b.record_failure()
        assert b.state == "open"
        now[0] = 11.0
        assert b.state == "half_open"
        assert b.allow()          # the probe slot
        assert not b.allow()      # only one probe at a time
        b.record_failure()        # probe failed: back to open
        assert b.state == "open" and b.trips == 2
        now[0] = 22.0
        assert b.allow()
        b.record_success()
        assert b.state == "closed" and b.allow()

    def test_cancel_probe_releases_the_slot(self):
        b, now = self._clocked(failure_threshold=1, reset_after_s=1.0)
        b.record_failure()
        now[0] = 2.0
        assert b.allow()
        b.cancel_probe()          # the probe never ran (e.g. 400 body)
        assert b.allow()          # slot is free again

    def test_transitions_journaled(self, tmp_path):
        with obs.run(tmp_path / "obs") as jr:
            b = breaker.CircuitBreaker(failure_threshold=1,
                                       reset_after_s=0.0, journal=jr)
            b.record_failure()
            assert b.allow()      # open -> half_open (cooldown 0)
            b.record_success()
        events = schema.read_events(jr.events_path)
        states = [e["state"] for e in events
                  if e["event"] == "circuit_state"]
        assert states == ["open", "half_open", "closed"]
        assert not any("_schema_error" in e for e in events)


class TestSeedableBackoff:
    def test_seeded_rng_reproduces_exact_schedule(self):
        mk = lambda: retry.RetryPolicy(base_delay_s=1.0, multiplier=2.0,
                                       max_delay_s=60.0, jitter=0.25,
                                       rng=random.Random(42))
        a, b = mk(), mk()
        sched_a = [a.delay(n) for n in range(1, 6)]
        sched_b = [b.delay(n) for n in range(1, 6)]
        assert sched_a == sched_b  # exact, not statistical
        # Jitter is real: the schedule is not the bare exponential curve.
        assert sched_a != [1.0, 2.0, 4.0, 8.0, 16.0]


class TestSupervisor:
    """Unit-level supervision with trivial (non-jax) children: fast tier-1
    coverage of the watchdog/escalation/restart/crash-loop machinery (the
    full training drill is the slow-marked ``supervisor.hang`` chaos
    leg)."""

    def _policy(self, **kw):
        kw.setdefault("poll_s", 0.05)
        kw.setdefault("grace_s", 1.0)
        kw.setdefault("backoff", retry.RetryPolicy(
            max_attempts=1_000_000, base_delay_s=0.0, jitter=0.0))
        return supervise.SupervisorPolicy(**kw)

    def _script(self, tmp_path, body: str) -> list:
        p = tmp_path / "child.py"
        p.write_text(body)
        return [sys.executable, str(p)]

    def test_preempted_exit_relaunches_with_resume(self, tmp_path):
        cmd = self._script(tmp_path, (
            "import sys\n"
            "sys.exit(0 if '--resume' in sys.argv else 75)\n"))
        with obs.run(tmp_path / "obs") as jr:
            sup = supervise.Supervisor(cmd, policy=self._policy(),
                                       journal=jr)
            assert sup.run() == 0
        assert sup.attempt == 2
        events = schema.read_events(jr.events_path)
        exits = [e for e in events if e["event"] == "supervisor_exit"]
        assert [e["classification"] for e in exits] == ["preempted",
                                                        "completed"]
        assert exits[0]["exit_code"] == preempt.EX_PREEMPTED
        restarts = [e for e in events if e["event"] == "supervisor_restart"]
        assert restarts[0]["resume"] is True
        assert restarts[0]["delay_s"] == 0.0  # preempted: no backoff
        launches = [e for e in events if e["event"] == "supervisor_launch"]
        assert "--resume" in launches[1]["cmd"]
        assert not any("_schema_error" in e for e in events)

    def test_hang_detected_term_escalation_and_relaunch(self, tmp_path):
        # The child beats once, then blocks SIGTERM-proof (signal ignored)
        # so the supervisor must escalate to SIGKILL.
        cmd = self._script(tmp_path, (
            "import json, os, signal, sys, time\n"
            "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
            "hb = os.environ['EEGTPU_HEARTBEAT_FILE']\n"
            "tmp = hb + '.tmp'\n"
            "open(tmp, 'w').write(json.dumps(\n"
            "    {'phase': 'step', 'beat': 1, 't': time.time(),\n"
            "     'pid': os.getpid()}))\n"
            "os.replace(tmp, hb)\n"
            "if '--resume' in sys.argv:\n"
            "    sys.exit(0)\n"
            "time.sleep(60)\n"))
        with obs.run(tmp_path / "obs") as jr:
            sup = supervise.Supervisor(
                cmd, policy=self._policy(
                    grace_s=0.4,
                    thresholds={"step": 0.3, "startup": 20.0}),
                heartbeat_file=tmp_path / "hb.json", journal=jr)
            assert sup.run() == 0
        events = schema.read_events(jr.events_path)
        kinds = [e["event"] for e in events]
        assert "supervisor_hang" in kinds
        assert "supervisor_escalate" in kinds  # SIGTERM was not enough
        hangs = [e for e in events if e["event"] == "supervisor_hang"]
        assert hangs[0]["phase"] == "step"
        assert hangs[0]["age_s"] > hangs[0]["threshold_s"]
        exits = [e for e in events if e["event"] == "supervisor_exit"]
        assert [e["classification"] for e in exits] == ["hang", "completed"]
        ends = [e for e in events if e["event"] == "supervisor_end"]
        assert ends[-1]["status"] == "completed"

    def test_crash_loop_breaker_gives_up(self, tmp_path):
        cmd = self._script(tmp_path, "import sys; sys.exit(1)\n")
        with obs.run(tmp_path / "obs") as jr:
            sup = supervise.Supervisor(
                cmd, policy=self._policy(max_restarts=2,
                                         restart_window_s=60.0),
                journal=jr)
            assert sup.run() == supervise.EX_CRASH_LOOP
        assert sup.attempt == 3  # initial + 2 restarts, then the verdict
        events = schema.read_events(jr.events_path)
        giveup = [e for e in events if e["event"] == "supervisor_giveup"]
        assert giveup and giveup[0]["restarts"] == 2
        ends = [e for e in events if e["event"] == "supervisor_end"]
        assert ends[-1]["status"] == "crash_loop"

    def test_fatal_exit_never_restarts(self, tmp_path):
        cmd = self._script(tmp_path, "import sys; sys.exit(2)\n")
        with obs.run(tmp_path / "obs") as jr:
            sup = supervise.Supervisor(cmd, policy=self._policy(),
                                       journal=jr)
            assert sup.run() == supervise.EX_FATAL
        assert sup.attempt == 1

    def test_transient_backoff_schedule_is_seeded_exact(self, tmp_path):
        # The satellite contract: a seeded rng makes the restart schedule
        # an exact assertion, not a sleep-through-jitter measurement.
        mk_policy = lambda: retry.RetryPolicy(
            max_attempts=1_000_000, base_delay_s=0.5, multiplier=2.0,
            max_delay_s=60.0, jitter=0.25, rng=random.Random(7))
        # Same seed, same DRAW SEQUENCE: delay(1) then delay(2) on one
        # policy instance, exactly as the supervisor consumes it.
        twin = mk_policy()
        expected = [twin.delay(1), twin.delay(2)]
        slept: list = []
        cmd = self._script(tmp_path, "import sys; sys.exit(1)\n")
        with obs.run(tmp_path / "obs") as jr:
            sup = supervise.Supervisor(
                cmd, policy=self._policy(max_restarts=2,
                                         backoff=mk_policy()),
                journal=jr, sleep=lambda s: slept.append(s))
            sup.run()
        events = schema.read_events(jr.events_path)
        delays = [e["delay_s"] for e in events
                  if e["event"] == "supervisor_restart"]
        assert delays == [round(d, 3) for d in expected]
        # The supervisor actually slept those exact delays (poll sleeps
        # are poll_s-sized; the backoff sleeps are the large ones).
        backoff_sleeps = [s for s in slept if s >= min(expected)]
        assert backoff_sleeps == expected

    def test_stop_request_forwards_and_ends_supervision(self, tmp_path):
        cmd = self._script(tmp_path, (
            "import signal, sys, time\n"
            "signal.signal(signal.SIGTERM, lambda *a: sys.exit(75))\n"
            "time.sleep(60)\n"))
        with obs.run(tmp_path / "obs") as jr:
            # poll_s long enough that the child has installed its handler
            # before the forwarded SIGTERM arrives.
            sup = supervise.Supervisor(cmd, policy=self._policy(poll_s=0.5),
                                       journal=jr)
            preempt.request("test-stop")
            code = sup.run()
        assert code == preempt.EX_PREEMPTED  # the child's drain exit code
        assert sup.attempt == 1  # no relaunch after our own stop
        events = schema.read_events(jr.events_path)
        ends = [e for e in events if e["event"] == "supervisor_end"]
        assert ends[-1]["status"] == "stopped"


class TestMultiSupervisor:
    """ISSUE-6 satellite: the multi-child supervision mode behind the
    replica fleet — kill one of three dummy children under load and ONLY
    that child restarts, siblings' heartbeats never go stale, and the
    crash-loop breaker fires per child."""

    BEATING_CHILD = (
        "import json, os, signal, sys, time\n"
        "signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))\n"
        "hb = os.environ.get('EEGTPU_HEARTBEAT_FILE')\n"
        "i = 0\n"
        "while True:\n"
        "    i += 1\n"
        "    if hb:\n"
        "        tmp = hb + '.tmp'\n"
        "        open(tmp, 'w').write(json.dumps(\n"
        "            {'phase': 'step', 'beat': i, 't': time.time(),\n"
        "             'pid': os.getpid()}))\n"
        "        os.replace(tmp, hb)\n"
        "    time.sleep(0.05)\n")

    def _policy(self, **kw):
        kw.setdefault("poll_s", 0.05)
        kw.setdefault("grace_s", 2.0)
        kw.setdefault("backoff", retry.RetryPolicy(
            max_attempts=1_000_000, base_delay_s=0.0, jitter=0.0))
        return supervise.SupervisorPolicy(**kw)

    def _specs(self, tmp_path, bodies: dict) -> list:
        specs = []
        for name, body in bodies.items():
            script = tmp_path / f"{name}.py"
            script.write_text(body)
            specs.append(supervise.ChildSpec(
                name=name, cmd=[sys.executable, str(script)],
                heartbeat_file=tmp_path / f"{name}.hb.json"))
        return specs

    @staticmethod
    def _wait(predicate, timeout_s=15.0, what="condition"):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if predicate():
                return
            time.sleep(0.02)
        raise AssertionError(f"timed out waiting for {what}")

    def test_kill_one_of_three_only_that_child_restarts(self, tmp_path):
        specs = self._specs(tmp_path, {f"c{i}": self.BEATING_CHILD
                                       for i in range(3)})
        with obs.run(tmp_path / "obs") as jr:
            sup = supervise.MultiSupervisor(
                specs, policy=self._policy(
                    thresholds={"step": 2.0, "startup": 30.0}),
                journal=jr)
            th = threading.Thread(target=sup.run, daemon=True)
            th.start()
            self._wait(lambda: all(
                c.state == "running" for c in sup.children.values()),
                what="all three children running")
            victim = sup.children["c1"]
            os.kill(victim.pid, 9)
            self._wait(lambda: victim.attempt == 2
                       and victim.state == "running",
                       what="victim relaunch")
            # A couple of watchdog cycles: the siblings keep beating and
            # must never be flagged stale while the victim bounces.
            time.sleep(0.5)
            assert sup.children["c0"].attempt == 1
            assert sup.children["c2"].attempt == 1
            sup.stop()
            th.join(timeout=15.0)
            assert not th.is_alive()
        events = schema.read_events(jr.events_path, complete=False)
        restarts = [e for e in events if e["event"] == "supervisor_restart"]
        assert [e["child"] for e in restarts] == ["c1"]
        assert restarts[0]["reason"] == "transient"  # SIGKILL, not hang
        assert not any(e["event"] == "supervisor_hang" for e in events)
        exits = [e for e in events if e["event"] == "supervisor_exit"]
        # 4 exits total: the kill + three drains at stop (and the
        # relaunched victim's drain).
        assert sum(1 for e in exits if e["child"] == "c1") == 2
        ends = [e for e in events if e["event"] == "supervisor_end"]
        assert ends[-1]["status"] == "stopped"
        assert not any("_schema_error" in e for e in events)

    def test_crash_loop_breaker_fires_per_child(self, tmp_path):
        specs = self._specs(tmp_path, {
            "looper": "import sys; sys.exit(1)\n",
            "worker": "import sys; sys.exit(0)\n"})
        with obs.run(tmp_path / "obs") as jr:
            sup = supervise.MultiSupervisor(
                specs, policy=self._policy(max_restarts=2,
                                           restart_window_s=60.0),
                journal=jr)
            assert sup.run() == supervise.EX_CRASH_LOOP
        assert sup.children["looper"].attempt == 3  # initial + 2 restarts
        assert sup.children["looper"].state == "crash_loop"
        assert sup.children["worker"].attempt == 1
        assert sup.children["worker"].state == "done"
        events = schema.read_events(jr.events_path, complete=False)
        giveups = [e for e in events if e["event"] == "supervisor_giveup"]
        assert [e["child"] for e in giveups] == ["looper"]
        ends = [e for e in events if e["event"] == "supervisor_end"]
        assert ends[-1]["status"] == "crash_loop"
        assert ends[-1]["children"] == {"looper": "crash_loop",
                                        "worker": "done"}

    def test_hang_detection_is_per_child(self, tmp_path):
        # One child beats once then wedges (SIGTERM-proof); the sibling
        # keeps beating.  Only the wedged child is escalated + relaunched.
        wedged = (
            "import json, os, signal, sys, time\n"
            "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
            "hb = os.environ['EEGTPU_HEARTBEAT_FILE']\n"
            "open(hb + '.tmp', 'w').write(json.dumps(\n"
            "    {'phase': 'step', 'beat': 1, 't': time.time(),\n"
            "     'pid': os.getpid()}))\n"
            "os.replace(hb + '.tmp', hb)\n"
            "if '--resume' not in sys.argv:\n"
            "    time.sleep(60)\n"
            "sys.exit(0)\n")
        specs = self._specs(tmp_path, {"wedge": wedged,
                                       "ok": self.BEATING_CHILD})
        with obs.run(tmp_path / "obs") as jr:
            sup = supervise.MultiSupervisor(
                specs, policy=self._policy(
                    grace_s=0.4, resume_arg="--resume",
                    thresholds={"step": 0.5, "startup": 30.0}),
                journal=jr)
            th = threading.Thread(target=sup.run, daemon=True)
            th.start()
            self._wait(lambda: sup.children["wedge"].state == "done",
                       what="wedged child killed, relaunched, completed")
            assert sup.children["ok"].attempt == 1
            sup.stop()
            th.join(timeout=15.0)
        events = schema.read_events(jr.events_path, complete=False)
        hangs = [e for e in events if e["event"] == "supervisor_hang"]
        assert hangs and all(e["child"] == "wedge" for e in hangs)
        assert any(e["event"] == "supervisor_escalate"
                   and e["child"] == "wedge" for e in events)
        exits = [e for e in events if e["event"] == "supervisor_exit"
                 and e["child"] == "wedge"]
        assert exits[0]["classification"] == "hang"


class TestDynamicMultiSupervisor:
    """ISSUE-17 satellite: the dynamic membership seam the autoscaler
    drives — ``add_child`` mid-run joins the supervision loop without
    disturbing siblings, ``retire_child`` removes exactly the named child
    with a clean ``supervisor_exit``, and a retired name re-added gets a
    brand-new child whose crash-loop breaker state is forgotten."""

    BEATING_CHILD = TestMultiSupervisor.BEATING_CHILD
    _policy = TestMultiSupervisor._policy
    _specs = TestMultiSupervisor._specs
    _wait = staticmethod(TestMultiSupervisor._wait)

    def _spec(self, tmp_path, name, body):
        return self._specs(tmp_path, {name: body})[0]

    def test_add_child_under_load_and_only_it_restarts(self, tmp_path):
        specs = self._specs(tmp_path, {"c0": self.BEATING_CHILD})
        with obs.run(tmp_path / "obs") as jr:
            sup = supervise.MultiSupervisor(
                specs, policy=self._policy(
                    thresholds={"step": 2.0, "startup": 30.0}),
                journal=jr)
            th = threading.Thread(target=sup.run, daemon=True)
            th.start()
            self._wait(lambda: sup.children["c0"].state == "running",
                       what="anchor child running")
            sup.add_child(self._spec(tmp_path, "c1", self.BEATING_CHILD))
            with pytest.raises(ValueError):
                sup.add_child(self._spec(tmp_path, "c1",
                                         self.BEATING_CHILD))
            self._wait(lambda: "c1" in sup.children
                       and sup.children["c1"].state == "running",
                       what="added child running")
            os.kill(sup.children["c1"].pid, 9)
            self._wait(lambda: sup.children["c1"].attempt == 2
                       and sup.children["c1"].state == "running",
                       what="added child relaunch")
            assert sup.children["c0"].attempt == 1
            sup.stop()
            th.join(timeout=15.0)
        events = schema.read_events(jr.events_path, complete=False)
        restarts = [e for e in events if e["event"] == "supervisor_restart"]
        assert [e["child"] for e in restarts] == ["c1"]
        assert not any("_schema_error" in e for e in events)

    def test_retire_child_removes_only_the_named_child(self, tmp_path):
        specs = self._specs(tmp_path, {f"c{i}": self.BEATING_CHILD
                                       for i in range(2)})
        with obs.run(tmp_path / "obs") as jr:
            sup = supervise.MultiSupervisor(
                specs, policy=self._policy(
                    thresholds={"step": 2.0, "startup": 30.0}),
                journal=jr)
            th = threading.Thread(target=sup.run, daemon=True)
            th.start()
            self._wait(lambda: all(
                c.state == "running" for c in sup.children.values()),
                what="both children running")
            assert sup.retire_child("c1", wait_s=15.0)
            assert "c1" not in sup.children
            assert sup.retire_child("c1")  # idempotent: already gone
            # The sibling never bounced — retirement is surgical.
            assert sup.children["c0"].state == "running"
            assert sup.children["c0"].attempt == 1
            sup.stop()
            th.join(timeout=15.0)
        events = schema.read_events(jr.events_path, complete=False)
        retired = [e for e in events if e["event"] == "supervisor_exit"
                   and e.get("classification") == "retired"]
        assert [e["child"] for e in retired] == ["c1"]
        assert not any(e["event"] == "supervisor_restart" for e in events)

    def test_breaker_state_is_forgotten_on_re_add(self, tmp_path):
        specs = self._specs(tmp_path, {"anchor": self.BEATING_CHILD,
                                       "flaky": "import sys; sys.exit(1)\n"})
        with obs.run(tmp_path / "obs") as jr:
            sup = supervise.MultiSupervisor(
                specs, policy=self._policy(
                    max_restarts=50, restart_window_s=60.0,
                    backoff=retry.RetryPolicy(
                        max_attempts=1_000_000, base_delay_s=0.25,
                        jitter=0.0),
                    thresholds={"step": 30.0, "startup": 30.0}),
                journal=jr)
            th = threading.Thread(target=sup.run, daemon=True)
            th.start()
            # Let the flaky child bank crashes in the breaker window.
            self._wait(lambda: "flaky" in sup.children
                       and sup.children["flaky"].attempt >= 2,
                       what="flaky child crashing")
            assert sup.retire_child("flaky", wait_s=15.0)
            sup.add_child(self._spec(tmp_path, "flaky",
                                     self.BEATING_CHILD))
            # The re-added name is a NEW child: attempt restarts at 1 and
            # the banked crash history cannot push it into the breaker.
            self._wait(lambda: sup.children["flaky"].state == "running",
                       what="re-added child running")
            assert sup.children["flaky"].attempt == 1
            sup.stop()
            th.join(timeout=15.0)
        events = schema.read_events(jr.events_path, complete=False)
        assert not any(e["event"] == "supervisor_giveup" for e in events)


class TestSupervisedResumeRegression:
    """ISSUE 5 satellite: a supervisor-driven kill + ``--resume`` relaunch
    reproduces the same final fold metrics as an uninterrupted run —
    through a REAL process boundary (the in-process twin lives in
    ``TestProtocolResilience.test_preempt_snapshots_and_resumes``)."""

    def _child_cmd(self, root: Path, chaos: str | None = None) -> list:
        cmd = [sys.executable, str(REPO / "scripts" / "chaos_drill.py"),
               "--child-train", "--root", str(root), "--epochs", "4"]
        if chaos:
            cmd += ["--chaos", chaos]
        return cmd

    def test_out_of_process_kill_resume_matches_uninterrupted(
            self, tmp_path):
        env = dict(os.environ, EEGTPU_NO_LOG_FILE="1")
        # Uninterrupted baseline through the SAME child entry point.
        base_root = tmp_path / "baseline"
        proc = subprocess.run(self._child_cmd(base_root), env=env,
                              capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
        baseline = json.loads((base_root / "result.json").read_text())

        # Supervised run: the armed host.preempt stops the child at its
        # first chunk boundary (exit EX_PREEMPTED, snapshot on disk); the
        # plan re-arms in every relaunch, so each resumed child advances
        # one chunk and is preempted again until only the eval remains —
        # three launches, two --resume relaunches, every one driven by
        # the supervisor's exit-code policy.
        sup_root = tmp_path / "supervised"
        with obs.run(tmp_path / "obs") as jr:
            sup = supervise.Supervisor(
                self._child_cmd(sup_root,
                                chaos="host.preempt:after=0:times=1"),
                policy=supervise.SupervisorPolicy(
                    poll_s=0.1, grace_s=10.0,
                    thresholds={"startup": 300.0, "compile": 300.0,
                                "step": 120.0}),
                heartbeat_file=sup_root / "heartbeat.json", journal=jr,
                env=env)
            assert sup.run() == 0
        assert sup.attempt == 3
        events = schema.read_events(jr.events_path)
        exits = [e["classification"] for e in events
                 if e["event"] == "supervisor_exit"]
        assert exits == ["preempted", "preempted", "completed"]
        result = json.loads((sup_root / "result.json").read_text())
        np.testing.assert_array_equal(np.asarray(result["fold_test_acc"]),
                                      np.asarray(baseline["fold_test_acc"]))
        # The final resumed child's own journal closed cleanly.
        child_runs = sorted((sup_root / "obs_child").iterdir())
        assert len(child_runs) == 3
        last = schema.read_events(child_runs[-1] / "events.jsonl")
        assert last[-1]["event"] == "run_end"
        assert last[-1]["status"] == "ok"


class TestSupervisionEventSummary:
    def _base(self, run_id="s1"):
        return [{"event": "run_start", "t": 1.0, "run_id": run_id,
                 "schema_version": 1, "git_sha": "abc", "platform": "cpu",
                 "device_kind": "cpu", "n_devices": 1, "config": {}}]

    def test_supervisor_fields(self):
        ev = self._base() + [
            {"event": "supervisor_start", "t": 2.0, "run_id": "s1",
             "cmd": ["x"]},
            {"event": "supervisor_hang", "t": 3.0, "run_id": "s1",
             "attempt": 1, "age_s": 9.0, "threshold_s": 3.0,
             "phase": "step"},
            {"event": "supervisor_restart", "t": 4.0, "run_id": "s1",
             "attempt": 1, "reason": "hang", "delay_s": 0.0,
             "resume": True},
            {"event": "supervisor_end", "t": 5.0, "run_id": "s1",
             "status": "completed"},
            {"event": "run_end", "t": 6.0, "run_id": "s1", "status": "ok",
             "wall_s": 5.0}]
        s = schema.event_summary(schema.validate_events(ev))
        assert s["supervisor_restarts"] == 1
        assert s["hang_detections"] == 1
        assert s["supervisor_status"] == "completed"

    def test_serving_expired_and_breaker_fields(self):
        req = {"event": "request", "run_id": "s1", "n_trials": 1,
               "latency_ms": 1.0}
        ev = self._base() + [
            dict(req, t=2.0, status="ok"),
            dict(req, t=3.0, status="expired"),
            dict(req, t=4.0, status="circuit_open"),
            {"event": "circuit_state", "t": 5.0, "run_id": "s1",
             "state": "open", "previous": "closed",
             "reason": "failure_threshold"},
            {"event": "circuit_state", "t": 6.0, "run_id": "s1",
             "state": "half_open", "previous": "open",
             "reason": "cooldown_elapsed"},
            {"event": "run_end", "t": 7.0, "run_id": "s1", "status": "ok",
             "wall_s": 6.0}]
        s = schema.event_summary(schema.validate_events(ev))
        assert s["n_requests"] == 3
        assert s["expired"] == 1
        assert s["circuit_refusals"] == 1
        assert s["request_errors"] == 0  # shed load is not an error
        assert s["breaker_trips"] == 1


class TestObsReportCrashedRuns:
    def _report(self, *args):
        return subprocess.run(
            [sys.executable, str(REPO / "scripts" / "obs_report.py"),
             "--json", *map(str, args)],
            capture_output=True, text=True, timeout=120,
            env=dict(os.environ, EEGTPU_NO_LOG_FILE="1"))

    def _run_start(self, run_id="r1"):
        return {"event": "run_start", "t": 1.0, "run_id": run_id,
                "schema_version": 1, "git_sha": "abc", "platform": "cpu",
                "device_kind": "cpu", "n_devices": 1, "config": {}}

    def test_crashed_run_with_truncated_tail_renders(self, tmp_path):
        run_dir = tmp_path / "r1"
        run_dir.mkdir()
        lines = [json.dumps(self._run_start()),
                 '{"event": "epoch", "t": 2.0, "run_id": "r1", "epo']  # cut
        (run_dir / "events.jsonl").write_text("\n".join(lines) + "\n")
        proc = self._report(run_dir)
        assert proc.returncode == 0, proc.stderr[-2000:]
        summary = json.loads(proc.stdout.strip())
        # Live and crashed are indistinguishable without a terminal event;
        # the honest shared label renders instead of raising.
        assert summary["status"] == "incomplete"
        assert "error" not in summary

    def test_preempted_run_renders(self, tmp_path):
        run_dir = tmp_path / "r2"
        run_dir.mkdir()
        lines = [json.dumps(self._run_start("r2")),
                 json.dumps({"event": "run_end", "t": 3.0, "run_id": "r2",
                             "status": "preempted", "wall_s": 2.0})]
        (run_dir / "events.jsonl").write_text("\n".join(lines) + "\n")
        proc = self._report(run_dir)
        assert proc.returncode == 0, proc.stderr[-2000:]
        summary = json.loads(proc.stdout.strip())
        assert summary["status"] == "preempted"


class TestTrainCLIChaosFlag:
    def test_bad_plan_fails_at_parse_time(self):
        proc = subprocess.run(
            [sys.executable, "-m", "eegnetreplication_tpu.train",
             "--chaos", "train.stpe:times=1"],
            capture_output=True, text=True, timeout=120, cwd=REPO,
            env=dict(os.environ, EEGTPU_NO_LOG_FILE="1",
                     EEGTPU_PLATFORM="cpu"))
        assert proc.returncode == 2  # argparse error, not a traceback
        assert "Unknown fault-injection site" in proc.stderr


@pytest.mark.slow
class TestChaosDrill:
    def test_drill_completes_all_legs(self, tmp_path):
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "chaos_drill.py"),
             "--root", str(tmp_path)],
            capture_output=True, text=True, timeout=1200,
            env=dict(os.environ, EEGTPU_NO_LOG_FILE="1",
                     EEGTPU_PLATFORM="cpu"))
        assert proc.returncode == 0, (proc.stdout[-3000:]
                                      + proc.stderr[-3000:])
        assert "ALL LEGS PASSED" in proc.stdout
