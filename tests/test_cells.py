"""Multi-cell serving (``eegnetreplication_tpu/serve/cells/``).

Covers the ISSUE-12 surface: cell-level membership (dark -> failed,
aggregate-SLO breach -> degraded, rejoin, the ``cell.partition``/
``refuse=`` chaos seam), the CellFront routing tier (least-loaded bulk
dispatch with the pinned header-forwarding set on every dispatch AND
failover retry, sticky session affinity), planned drain-migration
(export -> integrity-verified import -> affinity flip, ``session_migrate``
journaled), unplanned cross-cell failover from the snapshot spool with
the 409 replay-from-acked resync handshake (``cell_member failed``
pinned before ``session_failover``), the FleetApp session-affinity
forwarding that makes a fleet a session-capable cell, and the
``serve_bench.py --cells`` tier-1 selftest (zero window expirations on
planned migration, zero decision conflicts + bulk availability through
a cell SIGKILL).

The front/membership machinery is pure HTTP orchestration, so most
tests run against scriptable stdlib fake cells — no JAX; the end-to-end
truth (real engines, real processes, real SIGKILL) is the selftest leg
and the chaos drill's ``cell.failover`` leg.
"""

import io
import json
import os
import struct
import subprocess
import sys
import threading
import time
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import numpy as np
import pytest

from eegnetreplication_tpu.obs import journal as obs_journal
from eegnetreplication_tpu.obs import schema
from eegnetreplication_tpu.resil import inject
from eegnetreplication_tpu.serve.cells import membership as cms
from eegnetreplication_tpu.serve.cells.front import CellFront
from eegnetreplication_tpu.serve.cells.membership import (
    CellMember,
    CellMembership,
)
from eegnetreplication_tpu.serve.sessions import store as session_store
from eegnetreplication_tpu.serve.sessions.session import (
    StreamSession,
    WindowDecision,
)

REPO = Path(__file__).resolve().parent.parent


def _session_state(sid: str = "s1", acked: int = 160) -> dict:
    """A small but real StreamSession state (the export wire format is
    built from exactly this)."""
    session = StreamSession(sid, n_channels=2, window=16, hop=8,
                            ems_init_block_size=8)
    x = np.random.RandomState(7).randn(2, acked).astype(np.float32)
    for idx, start, win in session.ingest(x):
        session.record(WindowDecision(index=idx, start=start, pred=1,
                                      status="ok", latency_ms=1.0))
    return session.state_arrays()


def _tamper_payload_array(payload: bytes, name: str) -> bytes:
    """Flip one byte in the middle of ``name``'s compressed data inside
    a packed session export.  Targeting a real array entry (rather than
    a fixed byte offset) keeps the tamper meaningful as the state layout
    grows: a flip in zip bookkeeping like a mod-time field leaves the
    restored content byte-identical, which the content digest rightly
    accepts."""
    zi = zipfile.ZipFile(io.BytesIO(payload)).getinfo(name)
    # Local file header: data starts after the 30-byte fixed header plus
    # the filename and extra fields (lengths at offsets 26 and 28).
    n, m = struct.unpack(
        "<HH", payload[zi.header_offset + 26:zi.header_offset + 30])
    data_off = zi.header_offset + 30 + n + m
    bad = bytearray(payload)
    bad[data_off + zi.compress_size // 2] ^= 0xFF
    return bytes(bad)


class FakeCell:
    """A scriptable cell double: serve-protocol /healthz, /predict, and
    the /session/* surface the front forwards to.  Knobs are plain
    attributes mutated mid-test."""

    def __init__(self, port: int = 0):
        self.digest = "d0"
        self.degraded: list[str] = []       # non-empty -> healthz 503
        self.slo_any_breached = False
        self.queue_depth = 0
        self.predictions = [0, 1, 2]
        self.predict_status = 200
        self.sessions: dict[str, int] = {}  # sid -> acked advert
        self.export_payload: bytes | None = None
        self.import_status: int | None = None  # None = real behavior
        self.imports: list[bytes] = []
        self.log: list[tuple[str, bytes]] = []
        self.headers_log: list[tuple[str, dict]] = []
        fake = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.0"  # a stopped fake must look DEAD

            def log_message(self, *a):  # noqa: A003 — quiet
                pass

            def _reply(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _reply_octets(self, code, body):
                self.send_response(code)
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                parts = self.path.strip("/").split("/")
                if self.path == "/healthz":
                    code = 503 if fake.degraded else 200
                    self._reply(code, {
                        "status": "degraded" if fake.degraded else "ok",
                        "degraded": fake.degraded,
                        "variables_digest": fake.digest,
                        "queue_depth_requests": fake.queue_depth,
                        "sessions": len(fake.sessions),
                        "slo": {"breached": [],
                                "any_breached": fake.slo_any_breached}})
                    return
                if len(parts) == 3 and parts[0] == "session":
                    sid = parts[1]
                    if sid not in fake.sessions:
                        self._reply(404, {"error": "unknown session"})
                        return
                    if parts[2] == "state":
                        self._reply(200, {"session": sid,
                                          "acked": fake.sessions[sid],
                                          "windows": 0})
                        return
                    if parts[2] == "export":
                        payload = fake.export_payload
                        if payload is None:
                            payload = session_store.pack_session(
                                sid, _session_state(sid))
                        self._reply_octets(200, payload)
                        return
                self._reply(404, {})

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length", 0) or 0)
                body = self.rfile.read(n) if n else b""
                fake.log.append((self.path, body))
                fake.headers_log.append((self.path,
                                         dict(self.headers.items())))
                parts = self.path.strip("/").split("/")
                if self.path == "/predict":
                    if fake.predict_status != 200:
                        self._reply(fake.predict_status,
                                    {"error": "scripted"})
                        return
                    self._reply(200, {"predictions": fake.predictions,
                                      "n": len(fake.predictions),
                                      "model_digest": fake.digest})
                    return
                if self.path == "/session/open":
                    payload = json.loads(body.decode() or "{}")
                    sid = payload.get("session") or "anon"
                    resumed = sid in fake.sessions
                    fake.sessions.setdefault(sid, 0)
                    self._reply(200, {"session": sid,
                                      "acked": fake.sessions[sid],
                                      "windows": 0, "resumed": resumed})
                    return
                if self.path == "/session/import":
                    fake.imports.append(body)
                    if fake.import_status is not None:
                        self._reply(fake.import_status,
                                    {"error": "scripted"})
                        return
                    try:
                        sid, state = session_store.unpack_session(body)
                    except Exception as exc:  # noqa: BLE001
                        self._reply(400, {"error": str(exc)})
                        return
                    if sid in fake.sessions:
                        self._reply(409, {"error": "already open"})
                        return
                    restored = StreamSession.from_state(sid, state)
                    fake.sessions[sid] = restored.acked
                    self._reply(200, {"session": sid,
                                      "acked": restored.acked,
                                      "imported": True})
                    return
                if len(parts) == 3 and parts[0] == "session":
                    sid = parts[1]
                    if sid not in fake.sessions:
                        self._reply(404, {"error": "unknown session"})
                        return
                    if parts[2] == "samples":
                        self._reply(200, {"session": sid,
                                          "acked": fake.sessions[sid],
                                          "decisions": []})
                        return
                    if parts[2] in ("close", "discard"):
                        fake.sessions.pop(sid, None)
                        self._reply(200, {"session": sid, "windows": 0,
                                          "expired": 0, "acked": 0,
                                          "preds": []})
                        return
                self._reply(404, {})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def posts(self, path_suffix: str) -> list[bytes]:
        return [b for p, b in self.log if p.endswith(path_suffix)]

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


@pytest.fixture
def journal(tmp_path):
    with obs_journal.run(tmp_path / "obs", config={}) as jr:
        yield jr


def _members(fakes, journal, spools=None):
    spools = spools or [None] * len(fakes)
    return [CellMember(f"c{i}", fake.url, spool=spool, journal=journal)
            for i, (fake, spool) in enumerate(zip(fakes, spools))]


def _events(jr, kind):
    return [e for e in schema.read_events(jr.events_path, complete=False)
            if e["event"] == kind]


def _post(url, data=b"{}", ctype="application/json", headers=None):
    import urllib.request

    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": ctype, **(headers or {})})
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, json.loads(resp.read().decode())


def _get(url):
    import urllib.request

    with urllib.request.urlopen(url, timeout=30) as resp:
        return resp.status, json.loads(resp.read().decode())


# ---------------------------------------------------------------------------
# Cell membership: the fleet state machine one level up.


class TestCellMembership:
    def test_dark_cell_fails_and_rejoins_with_cell_member_events(
            self, journal):
        fake0, fake1 = FakeCell(), FakeCell()
        membership = CellMembership(_members([fake0, fake1], journal),
                                    journal=journal)
        membership.poll_once()
        assert [c.state for c in membership.replicas] == ["live", "live"]
        port = fake0.port
        fake0.stop()
        membership.poll_once()
        membership.poll_once()
        assert membership.by_id("c0").state == cms.FAILED
        assert membership.dispatchable() == [membership.by_id("c1")]
        # Same port, fresh process: the first healthy poll rejoins it.
        fake0b = FakeCell(port=port)
        membership.poll_once()
        assert membership.by_id("c0").state == "live"
        events = _events(journal, "cell_member")
        assert all("cell" in e for e in events)
        c0 = [(e["state"], e["reason"]) for e in events
              if e["cell"] == "c0"]
        assert ("failed", "unreachable: ConnectionRefusedError") in c0 \
            or any(s == "failed" for s, _ in c0)
        assert c0[-1][0] == "live" and c0[-1][1] == "rejoined"
        fake0b.stop()
        fake1.stop()
        membership.close()

    def test_aggregate_slo_breach_degrades_and_recovers(self, journal):
        fake0, fake1 = FakeCell(), FakeCell()
        membership = CellMembership(_members([fake0, fake1], journal),
                                    journal=journal)
        membership.poll_once()
        fake0.slo_any_breached = True
        membership.poll_once()
        cell = membership.by_id("c0")
        assert cell.state == "degraded" and cell.slo_any_breached
        assert membership.dispatchable() == [membership.by_id("c1")]
        fake0.slo_any_breached = False
        membership.poll_once()
        assert cell.state == "live"
        reasons = [e["reason"] for e in _events(journal, "cell_member")
                   if e["cell"] == "c0"]
        assert any(r.startswith("slo_breached") for r in reasons)
        fake0.stop()
        fake1.stop()
        membership.close()

    def test_healthz_503_degrades_cell(self, journal):
        fake = FakeCell()
        membership = CellMembership(_members([fake], journal),
                                    journal=journal)
        membership.poll_once()
        fake.degraded = ["circuit_open"]
        membership.poll_once()
        assert membership.by_id("c0").state == "degraded"
        fake.stop()
        membership.close()

    def test_partition_site_fails_exactly_one_tagged_cell(self, journal):
        fake0, fake1 = FakeCell(), FakeCell()
        membership = CellMembership(_members([fake0, fake1], journal),
                                    journal=journal)
        membership.poll_once()
        with inject.scoped(inject.FaultSpec(site="cell.partition",
                                            times=0, refuse=1,
                                            if_tag="c0")):
            membership.poll_once()
            membership.poll_once()
            assert membership.by_id("c0").state == cms.FAILED
            assert membership.by_id("c1").state == "live"
        membership.poll_once()
        assert membership.by_id("c0").state == "live"  # partition healed
        injected = _events(journal, "fault_injected")
        assert all(e["site"] == "cell.partition" for e in injected)
        fake0.stop()
        fake1.stop()
        membership.close()


# ---------------------------------------------------------------------------
# CellFront: bulk routing + the pinned header-forwarding set.


def _front(fakes, journal, spools=None, **kw):
    front = CellFront(_members(fakes, journal, spools), port=0,
                      poll_s=60.0, journal=journal, **kw)
    front.membership.poll_once()
    front.start()
    return front


PINNED_HEADERS = {
    "X-Model": "subject3",
    "X-Deadline-Ms": "750",
    "X-Priority": "high",
    "X-Trace-Id": "0123456789abcdef",
    "X-Trace-Sampled": "1",
}


class TestCellFrontRouting:
    @pytest.mark.parametrize("header", sorted(PINNED_HEADERS))
    def test_predict_forwards_pinned_header_set(self, journal, header):
        """The ISSUE-12 header audit: every client header in the pinned
        set must reach the cell on a dispatch (X-Trace-* through the
        propagation context, the rest verbatim)."""
        fake = FakeCell()
        front = _front([fake], journal)
        try:
            status, _ = _post(front.url + "/predict",
                              json.dumps({"trials": []}).encode(),
                              headers=PINNED_HEADERS)
            assert status == 200
            path, sent = [(p, h) for p, h in fake.headers_log
                          if p == "/predict"][0]
            assert sent.get(header) == PINNED_HEADERS[header], (header,
                                                                sent)
        finally:
            front.stop()
            fake.stop()

    @pytest.mark.parametrize("header", sorted(PINNED_HEADERS))
    def test_failover_retry_forwards_pinned_header_set(self, journal,
                                                       header):
        """...and the same set must survive a transport failover onto
        the sibling (the PR-10 regression, pinned one level up)."""
        fake0, fake1 = FakeCell(), FakeCell()
        front = _front([fake0, fake1], journal)
        try:
            fake0.stop()  # c0 is least-loaded first pick; dies on contact
            status, _ = _post(front.url + "/predict",
                              json.dumps({"trials": []}).encode(),
                              headers=PINNED_HEADERS)
            assert status == 200
            sent = [h for p, h in fake1.headers_log if p == "/predict"][0]
            assert sent.get(header) == PINNED_HEADERS[header], (header,
                                                                sent)
            assert front.membership.by_id("c0").state == cms.FAILED
        finally:
            front.stop()
            fake1.stop()

    def test_predict_routes_least_loaded(self, journal):
        fake0, fake1 = FakeCell(), FakeCell()
        fake0.queue_depth = 50
        front = _front([fake0, fake1], journal)
        try:
            front.membership.poll_once()  # pick up the queue depths
            _post(front.url + "/predict", json.dumps({"trials": []}).encode())
            assert len(fake1.posts("/predict")) == 1
            assert not fake0.posts("/predict")
        finally:
            front.stop()
            fake0.stop()
            fake1.stop()

    def test_no_live_cells_is_503(self, journal):
        import urllib.error

        fake = FakeCell()
        front = _front([fake], journal)
        try:
            fake.degraded = ["wedged"]
            front.membership.poll_once()
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(front.url + "/predict", b"{}")
            assert err.value.code == 503
        finally:
            front.stop()
            fake.stop()


class TestCellFrontSessions:
    def test_sticky_affinity_and_close_drops_it(self, journal):
        fake0, fake1 = FakeCell(), FakeCell()
        front = _front([fake0, fake1], journal)
        try:
            _, opened = _post(front.url + "/session/open",
                              json.dumps({"session": "s1"}).encode())
            home = opened["cell"]
            for _ in range(3):
                _post(front.url + "/session/s1/samples", b"{}")
            fakes = {"c0": fake0, "c1": fake1}
            assert len(fakes[home].posts("/samples")) == 3
            other = fakes["c1" if home == "c0" else "c0"]
            assert not other.posts("/samples")
            _post(front.url + "/session/s1/close")
            assert front.cell_of("s1") is None
            import urllib.error

            with pytest.raises(urllib.error.HTTPError) as err:
                _post(front.url + "/session/s1/samples", b"{}")
            assert err.value.code == 404
        finally:
            front.stop()
            fake0.stop()
            fake1.stop()

    def test_anonymous_open_gets_front_assigned_id(self, journal):
        fake = FakeCell()
        front = _front([fake], journal)
        try:
            _, opened = _post(front.url + "/session/open", b"{}")
            sid = opened["session"]
            assert sid and sid != "anon"  # the FRONT named it, not the fake
            assert front.cell_of(sid).cell_id == opened["cell"]
        finally:
            front.stop()
            fake.stop()

    def test_drain_migrates_flips_affinity_and_journals(self, journal):
        fake0, fake1 = FakeCell(), FakeCell()
        front = _front([fake0, fake1], journal)
        try:
            _, opened = _post(front.url + "/session/open",
                              json.dumps({"session": "s1"}).encode())
            fakes = {"c0": fake0, "c1": fake1}
            home = opened["cell"]
            target_id = "c1" if home == "c0" else "c0"
            status, result = _post(f"{front.url}/cell/{home}/drain")
            assert status == 200 and result["migrated"] == ["s1"], result
            # Export left the source, the import landed on the target,
            # and the source copy was discarded.
            assert fakes[target_id].imports
            assert fakes[home].posts("/discard")
            # Affinity flipped: samples now land on the target, with no
            # resync latch (the export was quiesced at the frontier).
            _post(front.url + "/session/s1/samples", b"{}")
            assert fakes[target_id].posts("/samples")
            assert not fakes[home].posts("/samples")
            # The drained cell is pinned out of bulk rotation...
            assert front.membership.by_id(home).state == "draining"
            front.membership.poll_once()  # ...and a healthy poll cannot
            assert front.membership.by_id(home).state == "draining"
            migrations = _events(journal, "session_migrate")
            assert [(e["session"], e["from_cell"], e["to_cell"])
                    for e in migrations] == [("s1", home, target_id)]
            # Undrain releases the pin and the poller re-LIVEs it.
            _post(f"{front.url}/cell/{home}/undrain")
            front.membership.poll_once()
            assert front.membership.by_id(home).state == "live"
        finally:
            front.stop()
            fake0.stop()
            fake1.stop()

    def test_tampered_migration_import_refused_session_stays(
            self, journal):
        """The integrity gate end-to-end: a tampered export is refused
        by the target (400) and the drain reports the session failed —
        still serving on the source."""
        fake0, fake1 = FakeCell(), FakeCell()
        front = _front([fake0, fake1], journal)
        try:
            _, opened = _post(front.url + "/session/open",
                              json.dumps({"session": "s1"}).encode())
            fakes = {"c0": fake0, "c1": fake1}
            home = opened["cell"]
            good = session_store.pack_session("s1", _session_state("s1"))
            fakes[home].export_payload = _tamper_payload_array(
                good, "s/s1/buf.npy")
            status, result = _post(f"{front.url}/cell/{home}/drain")
            assert status == 207 and result["failed"] == ["s1"], result
            assert front.cell_of("s1").cell_id == home
            assert not fakes[home].posts("/discard")
            assert not _events(journal, "session_migrate")
        finally:
            front.stop()
            fake0.stop()
            fake1.stop()

    def test_cell_kill_fails_over_from_spool_with_resync_handshake(
            self, journal, tmp_path):
        """The unplanned path end-to-end against fakes: kill the home
        cell -> lazy failover restores from its spool on the survivor ->
        the next /samples answers 409 (resume) -> a state read clears
        the latch -> samples flow again.  The journal pins cell_member
        failed before session_failover."""
        import urllib.error

        spool = tmp_path / "c0_spool"
        store = session_store.SessionStore(spool / "r0" / "sessions.npz")
        restored = StreamSession.from_state("s1", _session_state("s1"))
        store._sessions["s1"] = restored
        store.snapshot()
        store.detach()
        fake0, fake1 = FakeCell(), FakeCell()
        front = _front([fake0, fake1], journal, spools=[spool, None])
        try:
            fake0.queue_depth = 0
            fake1.queue_depth = 99  # pin the session's home to c0
            front.membership.poll_once()
            _, opened = _post(front.url + "/session/open",
                              json.dumps({"session": "s1"}).encode())
            assert opened["cell"] == "c0"
            fake1.queue_depth = 0
            fake0.stop()
            # First touch hits the dead cell: 503 while the failover
            # machinery reacts (mark_unreachable fired on the forward).
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(front.url + "/session/s1/samples", b"{}")
            assert err.value.code == 503
            assert front.membership.by_id("c0").state == cms.FAILED
            # Next touch: the session has failed over (lazily or via the
            # transition hook) and the resync latch answers 409.
            deadline = time.monotonic() + 10.0
            code = None
            while time.monotonic() < deadline:
                try:
                    _post(front.url + "/session/s1/samples", b"{}")
                    code = 200
                except urllib.error.HTTPError as e:
                    code = e.code
                if code == 409:
                    break
                time.sleep(0.05)
            assert code == 409
            assert fake1.imports, "no import reached the survivor"
            # The replay-from-acked handshake: a state read returns the
            # restored cursor and clears the latch.
            status, state = _get(front.url + "/session/s1/state")
            assert status == 200 and state["acked"] == 160
            status, _ = _post(front.url + "/session/s1/samples", b"{}")
            assert status == 200
            assert fake1.posts("/samples")
            events = schema.read_events(journal.events_path,
                                        complete=False)
            kinds = [e["event"] for e in events]
            failed_at = min(i for i, e in enumerate(events)
                            if e["event"] == "cell_member"
                            and e.get("state") == "failed")
            assert failed_at < kinds.index("session_failover")
            fo = _events(journal, "session_failover")[0]
            assert fo["from_cell"] == "c0" and fo["to_cell"] == "c1"
            assert fo["restored"] is True and fo["acked"] == 160
        finally:
            front.stop()
            fake1.stop()

    def test_failover_without_spool_reopens_from_zero(self, journal):
        """No snapshot survived: affinity still moves, the session is
        NOT restored, and the client's handshake lands on a fresh
        session (404 on state -> re-open) — still deterministic."""
        fake0, fake1 = FakeCell(), FakeCell()
        front = _front([fake0, fake1], journal)  # no spools at all
        try:
            fake1.queue_depth = 99
            front.membership.poll_once()
            _post(front.url + "/session/open",
                  json.dumps({"session": "s1"}).encode())
            fake1.queue_depth = 0
            fake0.stop()
            front.membership.poll_once()
            front.membership.poll_once()
            assert front.membership.by_id("c0").state == cms.FAILED
            deadline = time.monotonic() + 10.0
            while front.cell_of("s1").cell_id != "c1" \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
            assert front.cell_of("s1").cell_id == "c1"
            fo = _events(journal, "session_failover")[0]
            assert fo["restored"] is False
            assert not fake1.imports
            # The handshake: state 404s on the survivor, the client
            # re-opens through the front and replays from zero.
            import urllib.error

            with pytest.raises(urllib.error.HTTPError) as err:
                _get(front.url + "/session/s1/state")
            assert err.value.code == 404
            status, opened = _post(front.url + "/session/open",
                                   json.dumps({"session": "s1"}).encode())
            assert status == 200 and opened["acked"] == 0
            assert opened["cell"] == "c1"
            status, _ = _post(front.url + "/session/s1/samples", b"{}")
            assert status == 200
        finally:
            front.stop()
            fake1.stop()

    def test_healthz_reports_cells_and_sessions(self, journal):
        fake0, fake1 = FakeCell(), FakeCell()
        front = _front([fake0, fake1], journal)
        try:
            _post(front.url + "/session/open",
                  json.dumps({"session": "s1"}).encode())
            status, health = _get(front.url + "/healthz")
            assert status == 200
            assert health["n_cells"] == 2 and health["n_live"] == 2
            assert health["sessions"] == 1
            assert {c["cell"] for c in health["cells"]} == {"c0", "c1"}
        finally:
            front.stop()
            fake0.stop()
            fake1.stop()

    def test_event_summary_reports_cells_fields(self, journal):
        fake0, fake1 = FakeCell(), FakeCell()
        front = _front([fake0, fake1], journal)
        try:
            _, opened = _post(front.url + "/session/open",
                              json.dumps({"session": "s1"}).encode())
            _post(f"{front.url}/cell/{opened['cell']}/drain")
        finally:
            front.stop()
            fake0.stop()
            fake1.stop()
        summary = schema.event_summary(
            schema.read_events(journal.events_path, complete=False))
        assert summary["cells"] == 2
        assert summary["session_migrations"] == 1
        assert summary["session_failovers"] == 0
        assert summary["cell_member_transitions"] >= 2


# ---------------------------------------------------------------------------
# FleetApp as a session-capable cell: sticky replica forwarding.


class TestFleetSessionForwarding:
    def test_fleet_forwards_sessions_sticky_and_import_assigns(
            self, journal):
        from eegnetreplication_tpu.serve.fleet import membership as ms
        from eegnetreplication_tpu.serve.fleet.service import FleetApp

        fake0, fake1 = FakeCell(), FakeCell()  # speak the serve protocol
        replicas = [ms.Replica(f"r{i}", f.url, journal=journal)
                    for i, f in enumerate((fake0, fake1))]
        app = FleetApp(replicas, "ck.npz", port=0, poll_s=60.0,
                       journal=journal)
        app.membership.poll_once()
        app.start()
        try:
            _, opened = _post(app.url + "/session/open",
                              json.dumps({"session": "f1"}).encode())
            assert opened["session"] == "f1"
            for _ in range(2):
                _post(app.url + "/session/f1/samples", b"{}")
            served = [f for f in (fake0, fake1) if f.posts("/samples")]
            assert len(served) == 1 and len(served[0].posts("/samples")) == 2
            # Import lands on a replica and becomes sticky there.
            data = session_store.pack_session("f2", _session_state("f2"))
            status, reply = _post(app.url + "/session/import", data,
                                  ctype="application/octet-stream")
            assert status == 200 and reply["acked"] == 160
            status, state = _get(app.url + "/session/f2/state")
            assert status == 200 and state["acked"] == 160
            # Close drops stickiness.
            _post(app.url + "/session/f1/close")
            import urllib.error

            with pytest.raises(urllib.error.HTTPError) as err:
                _post(app.url + "/session/f1/samples", b"{}")
            assert err.value.code == 404
        finally:
            app.stop()
            fake0.stop()
            fake1.stop()

    def test_repeated_import_lands_on_the_same_replica(self, journal):
        # The cells front retries an import whose response was lost after
        # the fleet committed it, and relies on 409 = "the stream is
        # there".  A repeat must route to the replica that already holds
        # the session (409), never fork it onto a fresh least-loaded pick
        # (which would answer 200 from a second live copy).
        from eegnetreplication_tpu.serve.fleet import membership as ms
        from eegnetreplication_tpu.serve.fleet.service import FleetApp

        fake0, fake1 = FakeCell(), FakeCell()
        replicas = [ms.Replica(f"r{i}", f.url, journal=journal)
                    for i, f in enumerate((fake0, fake1))]
        app = FleetApp(replicas, "ck.npz", port=0, poll_s=60.0,
                       journal=journal)
        app.membership.poll_once()
        app.start()
        try:
            data = session_store.pack_session("f2", _session_state("f2"))
            status, _ = _post(app.url + "/session/import", data,
                              ctype="application/octet-stream")
            assert status == 200
            holder = next(f for f in (fake0, fake1)
                          if f.posts("/session/import"))
            other = fake1 if holder is fake0 else fake0
            import urllib.error

            with pytest.raises(urllib.error.HTTPError) as err:
                _post(app.url + "/session/import", data,
                      ctype="application/octet-stream")
            assert err.value.code == 409
            assert len(holder.posts("/session/import")) == 2
            assert not other.posts("/session/import")
        finally:
            app.stop()
            fake0.stop()
            fake1.stop()

    def test_fleet_parser_accepts_resume(self, capsys):
        # The cells supervisor relaunches a crashed fleet-shaped cell
        # with --resume appended; an unknown flag would argparse-exit 2
        # (in fatal_exit_codes) and retire the cell permanently.
        from eegnetreplication_tpu.serve.fleet import service as fleet_service

        with pytest.raises(SystemExit) as exc:
            fleet_service.main(["--help"])
        assert exc.value.code == 0
        assert "--resume" in capsys.readouterr().out

    def test_session_on_down_replica_answers_503_not_a_fork(self, journal):
        from eegnetreplication_tpu.serve.fleet import membership as ms
        from eegnetreplication_tpu.serve.fleet.service import FleetApp

        fake0, fake1 = FakeCell(), FakeCell()
        replicas = [ms.Replica(f"r{i}", f.url, journal=journal)
                    for i, f in enumerate((fake0, fake1))]
        app = FleetApp(replicas, "ck.npz", port=0, poll_s=60.0,
                       journal=journal)
        app.membership.poll_once()
        app.start()
        try:
            _, opened = _post(app.url + "/session/open",
                              json.dumps({"session": "f1"}).encode())
            sticky = app.session_replica("f1")
            fakes = {fake0.url: fake0, fake1.url: fake1}
            fakes[sticky.url].stop()
            import urllib.error

            with pytest.raises(urllib.error.HTTPError) as err:
                _post(app.url + "/session/f1/samples", b"{}")
            assert err.value.code == 503
            # A re-open while the sticky replica is down must NOT move
            # the session to a sibling (that would fork the stream).
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(app.url + "/session/open",
                      json.dumps({"session": "f1"}).encode())
            assert err.value.code == 503
            assert app.session_replica("f1") is sticky
            survivor = fake1 if fakes[sticky.url] is fake0 else fake0
            assert not survivor.posts("/session/open") \
                or len(survivor.posts("/session/open")) == 0
        finally:
            app.stop()
            fake0.stop()
            fake1.stop()


# ---------------------------------------------------------------------------
# The tier-1 selftest: real engines, real processes, real SIGKILL.


class TestCellsBenchSelftest:
    def test_cells_selftest_passes(self, tmp_path):
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "serve_bench.py"),
             "--cells", "--selftest",
             "--cellsOut", str(tmp_path / "BENCH_CELLS_selftest.json")],
            capture_output=True, text=True, timeout=420,
            env=dict(os.environ, EEGTPU_NO_LOG_FILE="1",
                     EEGTPU_PLATFORM="cpu", JAX_PLATFORMS="cpu"))
        assert proc.returncode == 0, (proc.stdout[-4000:]
                                      + proc.stderr[-2000:])
        assert "SELFTEST PASS" in proc.stdout
        record = json.loads(
            (tmp_path / "BENCH_CELLS_selftest.json").read_text())
        assert record["migration"]["window_expirations"] == 0
        assert record["migration"]["decisions_equal"]
        assert record["cell_kill"]["decisions_equal"]
        assert record["cell_kill"]["duplicate_conflicts"] == 0
        assert record["cell_kill"]["bulk"]["failures"] == 0
        assert record["cell_kill"]["journal_order_ok"]
