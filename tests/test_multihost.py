"""True multi-process test of the distributed backend (DCN-path twin).

Round 1 shipped ``initialize_distributed`` / ``make_hybrid_mesh`` untested
("no hardware").  No hardware is still true — but ``jax.distributed`` works
across *processes* on the CPU backend, which exercises the identical
code path (coordinator bring-up, global device view, cross-process
collectives) that a TPU pod's DCN uses.  Two local processes with 4 virtual
devices each form a (4 fold, 2 data) hybrid mesh and run a psum over the
full 8-device global mesh.
"""

import os
import socket
import subprocess
import sys
import unittest
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]

WORKER = r"""
import sys
port, pid = sys.argv[1], int(sys.argv[2])

from eegnetreplication_tpu.utils.platform import force_cpu
force_cpu(4)  # 4 virtual CPU devices per process, before any backend init

from eegnetreplication_tpu.parallel.mesh import (
    DATA_AXIS, FOLD_AXIS, initialize_distributed, make_hybrid_mesh,
)
initialize_distributed(f"127.0.0.1:{port}", num_processes=2, process_id=pid)

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()

mesh = make_hybrid_mesh(n_data_per_host=2)
assert dict(mesh.shape) == {FOLD_AXIS: 4, DATA_AXIS: 2}, dict(mesh.shape)

def f(x):
    # reduce over BOTH axes: crosses the process (DCN-analog) boundary
    return jax.lax.psum(jax.lax.psum(x, FOLD_AXIS), DATA_AXIS)

fm = jax.jit(shard_map(f, mesh=mesh, in_specs=P(FOLD_AXIS, DATA_AXIS),
                       out_specs=P(FOLD_AXIS, DATA_AXIS)))
with mesh:
    x = jax.device_put(
        jnp.ones((8, 2), jnp.float32),
        NamedSharding(mesh, P(FOLD_AXIS, DATA_AXIS)))
    out = fm(x)
    # every element is the sum over all 8 shards' ones * their block size
    total = float(jax.block_until_ready(out).max())
assert total == 8.0, total
print(f"proc {pid} OK: global psum over hybrid mesh = {total}")
"""


class TestMultiProcessBackend(unittest.TestCase):
    def test_two_process_hybrid_mesh_psum(self):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        env = dict(os.environ, PYTHONPATH=str(REPO), EEGTPU_NO_LOG_FILE="1")
        env.pop("JAX_PLATFORMS", None)
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", WORKER, str(port), str(pid)],
                cwd=REPO, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
            for pid in (0, 1)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=300)
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for p, out in zip(procs, outs):
            self.assertEqual(p.returncode, 0, out[-3000:])
        self.assertIn("proc 0 OK", outs[0] + outs[1])
        self.assertIn("proc 1 OK", outs[0] + outs[1])



TRAIN_WORKER = r"""
import sys
port, pid = sys.argv[1], int(sys.argv[2])
from eegnetreplication_tpu.utils.platform import force_cpu
force_cpu(4)
from eegnetreplication_tpu.parallel.mesh import (
    initialize_distributed, make_hybrid_mesh,
)
initialize_distributed(f"127.0.0.1:{port}", num_processes=2, process_id=pid)
import jax, jax.numpy as jnp, numpy as np
from eegnetreplication_tpu.models import EEGNet
from eegnetreplication_tpu.training import (
    init_fold_states, make_fold_spec, make_multi_fold_trainer, make_optimizer,
)
mesh = make_hybrid_mesh(n_data_per_host=1)  # 8 global folds over 2 hosts
C, T, B = 6, 64, 8
rng = np.random.RandomState(0)
px = jnp.asarray(rng.randn(64, C, T), jnp.float32)
py = jnp.asarray(rng.randint(0, 4, 64), jnp.int32)
model = EEGNet(n_channels=C, n_times=T)
tx = make_optimizer()
trainer = make_multi_fold_trainer(model, tx, batch_size=B, epochs=1,
                                  train_pad=32, val_pad=16, test_pad=16,
                                  mesh=mesh)
idx = np.arange(64)
specs = [make_fold_spec(idx[:32], idx[32:48], idx[48:], train_pad=32,
                        val_pad=16, test_pad=16) for _ in range(8)]
stacked = jax.tree_util.tree_map(lambda *l: jnp.stack(l), *specs)
states = init_fold_states(model, tx, 8, (C, T))
res = jax.block_until_ready(trainer(
    px, py, stacked, states, jax.random.split(jax.random.PRNGKey(0), 8)))
assert res.val_accuracies.shape == (8, 1), res.val_accuracies.shape
print(f"proc {pid} TRAIN OK")
"""


class TestMultiProcessTraining(unittest.TestCase):
    def test_fold_sharded_training_across_processes(self):
        """The actual product path: the fused fold trainer sharded over a
        hybrid mesh whose fold axis spans the process (DCN) boundary."""
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        env = dict(os.environ, PYTHONPATH=str(REPO), EEGTPU_NO_LOG_FILE="1")
        env.pop("JAX_PLATFORMS", None)
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", TRAIN_WORKER, str(port), str(pid)],
                cwd=REPO, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
            for pid in (0, 1)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=300)
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for p, out in zip(procs, outs):
            self.assertEqual(p.returncode, 0, out[-3000:])
        joined = "".join(outs)
        self.assertIn("proc 0 TRAIN OK", joined)
        self.assertIn("proc 1 TRAIN OK", joined)

if __name__ == "__main__":
    unittest.main()
