"""True multi-process tests of the distributed backend (DCN-path twin).

Round 1 shipped ``initialize_distributed`` / ``make_hybrid_mesh`` untested
("no hardware").  No hardware is still true — but ``jax.distributed`` works
across *processes* on the CPU backend, which exercises the identical
code path (coordinator bring-up, global device view, cross-process
collectives) that a TPU pod's DCN uses.  Two local processes with 4 virtual
devices each form a hybrid mesh over all 8 devices; one test checks a psum
crossing the process boundary, the other trains the fused fold trainer over
the mesh and asserts numeric equivalence with the unsharded run.
"""

import os
import socket
import subprocess
import sys
import unittest

import pytest
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]

PSUM_WORKER = r"""
import sys
port, pid = sys.argv[1], int(sys.argv[2])

from eegnetreplication_tpu.utils.platform import force_cpu
force_cpu(4)  # 4 virtual CPU devices per process, before any backend init

from eegnetreplication_tpu.parallel.mesh import (
    DATA_AXIS, FOLD_AXIS, MODEL_AXIS, initialize_distributed,
    make_hybrid_mesh,
)
initialize_distributed(f"127.0.0.1:{port}", num_processes=2, process_id=pid)

import jax
import jax.numpy as jnp
from eegnetreplication_tpu.utils.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()

mesh = make_hybrid_mesh(n_data_per_host=2)
assert dict(mesh.shape) == {FOLD_AXIS: 4, DATA_AXIS: 2, MODEL_AXIS: 1}, \
    dict(mesh.shape)

def f(x):
    # reduce over BOTH axes: crosses the process (DCN-analog) boundary
    return jax.lax.psum(jax.lax.psum(x, FOLD_AXIS), DATA_AXIS)

fm = jax.jit(shard_map(f, mesh=mesh, in_specs=P(FOLD_AXIS, DATA_AXIS),
                       out_specs=P(FOLD_AXIS, DATA_AXIS)))
with mesh:
    x = jax.device_put(
        jnp.ones((8, 2), jnp.float32),
        NamedSharding(mesh, P(FOLD_AXIS, DATA_AXIS)))
    out = fm(x)
    total = float(jax.block_until_ready(out).max())
assert total == 8.0, total
print(f"proc {pid} OK: global psum over hybrid mesh = {total}")
"""

TRAIN_WORKER = r"""
import sys
port, pid = sys.argv[1], int(sys.argv[2])
from eegnetreplication_tpu.utils.platform import force_cpu
force_cpu(4)
from eegnetreplication_tpu.parallel.mesh import (
    initialize_distributed, make_hybrid_mesh,
)
initialize_distributed(f"127.0.0.1:{port}", num_processes=2, process_id=pid)
import jax, jax.numpy as jnp, numpy as np
from eegnetreplication_tpu.models import EEGNet
from eegnetreplication_tpu.training import (
    init_fold_states, make_fold_spec, make_multi_fold_trainer, make_optimizer,
)
mesh = make_hybrid_mesh(n_data_per_host=1)  # 8 global folds over 2 hosts
C, T, B = 6, 64, 8
rng = np.random.RandomState(0)
px = jnp.asarray(rng.randn(64, C, T), jnp.float32)
py = jnp.asarray(rng.randint(0, 4, 64), jnp.int32)
model = EEGNet(n_channels=C, n_times=T)
tx = make_optimizer()
idx = np.arange(64)
specs = [make_fold_spec(idx[:32], idx[32:48], idx[48:], train_pad=32,
                        val_pad=16, test_pad=16) for _ in range(8)]
stacked = jax.tree_util.tree_map(lambda *l: jnp.stack(l), *specs)
states = init_fold_states(model, tx, 8, (C, T))
keys = jax.random.split(jax.random.PRNGKey(0), 8)

kw = dict(batch_size=B, epochs=1, train_pad=32, val_pad=16, test_pad=16)
sharded = jax.block_until_ready(make_multi_fold_trainer(
    model, tx, mesh=mesh, **kw)(px, py, stacked, states, keys))
# Numeric equivalence: the same program unsharded (plain vmap, local) must
# produce the same metrics — a collective bug that garbles remote folds'
# results would diverge here, not just change a shape.
local = jax.block_until_ready(make_multi_fold_trainer(
    model, tx, **kw)(px, py, stacked, states, keys))
from jax.experimental import multihost_utils
for name in ("val_accuracies", "test_accuracy", "train_losses"):
    # the sharded metrics span both processes: gather the global value
    a = np.asarray(multihost_utils.process_allgather(
        getattr(sharded, name), tiled=True))
    b = np.asarray(getattr(local, name))
    assert np.all(np.isfinite(a)), (name, a)
    np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4, err_msg=name)
print(f"proc {pid} TRAIN OK: sharded == unsharded")
"""


def run_two_process_workers(worker_src: str, timeout: int = 300):
    """Launch worker_src in 2 coordinated processes; return their outputs.

    Raises AssertionError with the failing worker's output on nonzero exit.
    """
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ, PYTHONPATH=str(REPO), EEGTPU_NO_LOG_FILE="1")
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", worker_src, str(port), str(pid)],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-3000:]
    return outs


class TestMultiProcessBackend(unittest.TestCase):
    @pytest.mark.slow
    def test_two_process_hybrid_mesh_psum(self):
        outs = run_two_process_workers(PSUM_WORKER)
        joined = "".join(outs)
        self.assertIn("proc 0 OK", joined)
        self.assertIn("proc 1 OK", joined)


class TestMultiProcessTraining(unittest.TestCase):
    @pytest.mark.slow
    def test_fold_sharded_training_across_processes(self):
        """The actual product path: the fused fold trainer sharded over a
        hybrid mesh whose fold axis spans the process (DCN) boundary,
        numerically equivalent to the unsharded run."""
        outs = run_two_process_workers(TRAIN_WORKER)
        joined = "".join(outs)
        self.assertIn("proc 0 TRAIN OK", joined)
        self.assertIn("proc 1 TRAIN OK", joined)


if __name__ == "__main__":
    unittest.main()
