"""Tests for the EMS op.

Mirrors and extends the reference's EMS property tests
(``tests/test_dataset.py:53-106``), and adds golden parity against a float64
numpy evaluation of the recurrences the reference defines at
``dataset.py:45-70``.
"""

import numpy as np
import pytest

from eegnetreplication_tpu.ops.ems import (
    exponential_moving_standardize,
    raw_exponential_moving_standardize,
)


def numpy_ems_reference(x, factor_new=1e-3, init_block_size=1000, eps=1e-10):
    """Sequential float64 evaluation of the EMS recurrences (ground truth)."""
    x = np.asarray(x, dtype=np.float64)
    out = np.zeros_like(x)
    mean = np.mean(x[..., :init_block_size], axis=-1)
    var = np.var(x[..., :init_block_size], axis=-1)
    a = factor_new
    for t in range(x.shape[-1]):
        mean = (1 - a) * mean + a * x[..., t]
        var = (1 - a) * var + a * (x[..., t] - mean) ** 2
        out[..., t] = (x[..., t] - mean) / np.sqrt(var + eps)
    return out


@pytest.fixture
def signal():
    rng = np.random.RandomState(0)
    return rng.randn(4, 3000).astype(np.float32) * 5.0 + 2.0


class TestEMSParity:
    def test_associative_matches_float64_loop(self, signal):
        got = np.asarray(exponential_moving_standardize(signal, init_block_size=1000))
        want = numpy_ems_reference(signal, init_block_size=1000)
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)

    def test_scan_matches_float64_loop(self, signal):
        got = np.asarray(
            exponential_moving_standardize(signal, init_block_size=1000, method="scan")
        )
        want = numpy_ems_reference(signal, init_block_size=1000)
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)

    def test_associative_matches_scan(self, signal):
        a = np.asarray(exponential_moving_standardize(signal))
        b = np.asarray(exponential_moving_standardize(signal, method="scan"))
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)

    def test_numpy_shim_signature(self, signal):
        out = raw_exponential_moving_standardize(signal)
        assert isinstance(out, np.ndarray)
        assert out.shape == signal.shape


class TestEMSProperties:
    """Property tests mirroring reference tests/test_dataset.py:53-106."""

    def test_shape_preserved(self, signal):
        assert exponential_moving_standardize(signal).shape == signal.shape

    def test_tail_approximately_standardized(self):
        rng = np.random.RandomState(1)
        x = (rng.randn(2, 20000) * 7.0 + 3.0).astype(np.float32)
        out = np.asarray(exponential_moving_standardize(x))
        tail = out[:, -5000:]
        assert np.all(np.abs(tail.mean(axis=1)) < 0.15)
        assert np.all(np.abs(tail.std(axis=1) - 1.0) < 0.2)

    def test_sensitive_to_factor_new(self, signal):
        a = np.asarray(exponential_moving_standardize(signal, factor_new=1e-3))
        b = np.asarray(exponential_moving_standardize(signal, factor_new=1e-1))
        assert not np.allclose(a, b)

    def test_sensitive_to_init_block_size(self, signal):
        a = np.asarray(exponential_moving_standardize(signal, init_block_size=10))
        b = np.asarray(exponential_moving_standardize(signal, init_block_size=1000))
        assert not np.allclose(a, b)

    def test_single_channel(self):
        x = np.random.RandomState(2).randn(1, 500).astype(np.float32)
        out = np.asarray(exponential_moving_standardize(x, init_block_size=100))
        want = numpy_ems_reference(x, init_block_size=100)
        np.testing.assert_allclose(out, want, rtol=2e-3, atol=2e-3)

    def test_constant_signal_is_finite(self):
        x = np.full((3, 400), 5.0, dtype=np.float32)
        out = np.asarray(exponential_moving_standardize(x, init_block_size=100))
        assert np.all(np.isfinite(out))
        np.testing.assert_allclose(out, 0.0, atol=1e-3)

    def test_init_block_larger_than_signal(self):
        x = np.random.RandomState(3).randn(2, 50).astype(np.float32)
        out = np.asarray(exponential_moving_standardize(x, init_block_size=1000))
        want = numpy_ems_reference(x, init_block_size=50)
        np.testing.assert_allclose(out, want, rtol=2e-3, atol=2e-3)


class TestPallasEMS:
    """The single-HBM-pass Pallas kernel (ops/ems_pallas.py) must be a
    drop-in numeric twin of the scan formulations (interpreter mode off-TPU,
    the real Mosaic kernel on chip)."""

    def test_matches_float64_loop(self, signal):
        got = np.asarray(exponential_moving_standardize(
            signal, init_block_size=1000, method="pallas"))
        want = numpy_ems_reference(signal, init_block_size=1000)
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)

    def test_matches_scan_tightly(self, signal):
        a = np.asarray(exponential_moving_standardize(signal,
                                                      method="pallas"))
        b = np.asarray(exponential_moving_standardize(signal, method="scan"))
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)

    def test_ragged_tail_and_custom_block(self):
        """T not a multiple of the time block: the pad must not leak."""
        from eegnetreplication_tpu.ops.ems_pallas import ems_pallas

        x = np.random.RandomState(7).randn(3, 700).astype(np.float32)
        got = np.asarray(ems_pallas(x, block_t=256))
        want = numpy_ems_reference(x, init_block_size=700)
        assert got.shape == x.shape
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)

    def test_carry_crosses_blocks(self):
        """A block boundary must be invisible: one block vs many."""
        from eegnetreplication_tpu.ops.ems_pallas import ems_pallas

        x = np.random.RandomState(9).randn(2, 1024).astype(np.float32)
        one = np.asarray(ems_pallas(x, block_t=1024))
        many = np.asarray(ems_pallas(x, block_t=128))
        np.testing.assert_allclose(one, many, rtol=1e-4, atol=1e-4)

    def test_rejects_non_2d(self):
        from eegnetreplication_tpu.ops.ems_pallas import ems_pallas

        with pytest.raises(ValueError, match=r"\(C, T\)"):
            ems_pallas(np.zeros((2, 3, 4), np.float32))

    def test_probe(self):
        from eegnetreplication_tpu.ops.ems_pallas import probe_ems_pallas

        assert probe_ems_pallas() is True
