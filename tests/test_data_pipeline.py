"""Tests for the native data pipeline: GDF reader, DSP ops, epoching, CLI.

The reference has no tests for its data-acquisition path at all (SURVEY.md
§4); these cover the framework's native replacements end to end on synthetic
GDF files (no real data, no network).
"""

import shutil
import tempfile
import unittest
from pathlib import Path

import numpy as np

from eegnetreplication_tpu.config import Paths
from eegnetreplication_tpu.data.epoching import (
    CUE_UNKNOWN,
    TRAIN_CUE_TO_CLASS,
    extract_epochs,
    load_true_labels,
    map_labels,
)
from eegnetreplication_tpu.data.gdf import GDFRecording, read_gdf_python, write_gdf
from eegnetreplication_tpu.data.preprocess import (
    ProcessedRecording,
    preprocess_recording,
)
from eegnetreplication_tpu.ops.dsp import (
    fir_bandpass,
    mne_style_bandpass_design,
    resample_fft,
)


class TestGDFReader(unittest.TestCase):
    def _roundtrip(self, version):
        rng = np.random.RandomState(7)
        sig = rng.uniform(-0.9, 0.9, (25, 250 * 6)).astype(np.float32)
        pos = np.array([100, 500, 900, 1300])
        typ = np.array([768, 769, 772, 1023])
        with tempfile.TemporaryDirectory() as d:
            p = write_gdf(Path(d) / "A01T.gdf", sig, 250.0, event_pos=pos,
                          event_typ=typ, version=version)
            rec = read_gdf_python(p)
        self.assertEqual(rec.signals.shape, (25, 1500))
        np.testing.assert_allclose(rec.signals, sig, atol=1e-6)
        np.testing.assert_array_equal(rec.event_pos, pos)
        np.testing.assert_array_equal(rec.event_typ, typ)
        self.assertEqual(rec.sfreq, 250.0)

    def test_roundtrip_v2(self):
        self._roundtrip("2.20")

    def test_roundtrip_v1(self):
        self._roundtrip("1.25")

    def test_rejects_non_gdf(self):
        with tempfile.TemporaryDirectory() as d:
            p = Path(d) / "junk.gdf"
            p.write_bytes(b"\x00" * 512)
            with self.assertRaises(ValueError):
                read_gdf_python(p)


class TestDSP(unittest.TestCase):
    def test_resample_preserves_tone(self):
        t = np.arange(0, 8, 1 / 250.0)
        sig = np.sin(2 * np.pi * 10 * t).astype(np.float32)
        num = int(round(len(sig) * 128 / 250))
        out = np.asarray(resample_fft(sig, num))
        t2 = np.arange(num) / 128.0
        ref = np.sin(2 * np.pi * 10 * t2)
        self.assertLess(np.abs(out[64:-64] - ref[64:-64]).max(), 1e-3)

    def test_resample_matches_scipy(self):
        from scipy.signal import resample as scipy_resample

        rng = np.random.RandomState(5)
        x = rng.randn(3, 1000).astype(np.float32)
        for num in (512, 513, 2000, 2001):  # down/up, even/odd targets
            ours = np.asarray(resample_fft(x, num))
            ref = scipy_resample(x.astype(np.float64), num, axis=-1)
            np.testing.assert_allclose(ours, ref, atol=1e-4)

    def test_bandpass_design_matches_mne_length(self):
        # MNE's auto design at 128 Hz / 4-38 Hz: min trans bw 2 Hz ->
        # ceil(3.3 * 128 / 2) = 212 -> odd 213 taps.
        k = mne_style_bandpass_design(128.0, 4.0, 38.0)
        self.assertEqual(len(k), 213)
        self.assertAlmostEqual(float(np.sum(np.abs(k - k[::-1]))), 0.0,
                               places=6)  # symmetric -> linear phase

    def test_bandpass_frequency_response(self):
        t = np.arange(0, 8, 1 / 128.0)
        x = np.stack([np.sin(2 * np.pi * f * t) for f in (1.0, 20.0, 55.0)])
        y = np.asarray(fir_bandpass(x.astype(np.float32), 128.0))
        rms = np.sqrt((y[:, 150:-150] ** 2).mean(axis=1))
        self.assertLess(rms[0], 0.02)           # 1 Hz: stopband
        self.assertAlmostEqual(rms[1], 2 ** -0.5, delta=0.02)  # 20 Hz: pass
        self.assertLess(rms[2], 0.02)           # 55 Hz: stopband

    def test_bandpass_zero_phase(self):
        # A passband tone must come out with (close to) zero delay.
        t = np.arange(0, 8, 1 / 128.0)
        x = np.sin(2 * np.pi * 15 * t).astype(np.float32)
        y = np.asarray(fir_bandpass(x, 128.0))
        xc = np.correlate(y[200:-200], x[200:-200], "full")
        lag = int(np.argmax(xc)) - (len(x[200:-200]) - 1)
        self.assertEqual(lag, 0)


class TestPreprocessRecording(unittest.TestCase):
    def test_shapes_events_and_standardization(self):
        rng = np.random.RandomState(3)
        sfreq, secs = 250.0, 20
        n = int(sfreq * secs)
        sig = rng.randn(25, n).astype(np.float32)
        sig[22:] += 50.0  # EOG channels: junk that must be dropped
        sig[0, 1000:1010] = np.nan  # artifact span
        rec = GDFRecording(signals=sig, sfreq=sfreq,
                           labels=[f"c{i}" for i in range(25)],
                           event_pos=np.array([500, 2500]),
                           event_typ=np.array([769, 770]))
        out = preprocess_recording(rec)
        self.assertEqual(out.data.shape[0], 22)
        self.assertEqual(out.data.shape[1], int(round(n * 128 / 250)))
        self.assertTrue(np.all(np.isfinite(out.data)))
        np.testing.assert_array_equal(
            out.event_pos, np.round(rec.event_pos * 128 / 250).astype(int))
        # EMS output is approximately standardized in the tail.
        tail = out.data[:, -500:]
        self.assertLess(np.abs(tail.mean()), 0.5)

    def test_ems_method_env_knob(self):
        """EEGTPU_EMS_METHOD routes the EMS formulation: the pallas kernel
        must agree with the default, and an unknown name must surface."""
        import os
        from unittest import mock

        rng = np.random.RandomState(4)
        rec = GDFRecording(signals=rng.randn(25, 3000).astype(np.float32),
                           sfreq=250.0,
                           labels=[f"c{i}" for i in range(25)],
                           event_pos=np.array([500]),
                           event_typ=np.array([769]))
        default = preprocess_recording(rec)
        with mock.patch.dict(os.environ, {"EEGTPU_EMS_METHOD": "pallas"}):
            pallas = preprocess_recording(rec)
        np.testing.assert_allclose(pallas.data, default.data,
                                   rtol=1e-3, atol=1e-3)
        with mock.patch.dict(os.environ, {"EEGTPU_EMS_METHOD": "bogus"}), \
             self.assertRaisesRegex(ValueError, "Unknown EMS method"):
            preprocess_recording(rec)

    def test_save_load_roundtrip(self):
        pr = ProcessedRecording(
            data=np.ones((22, 100), np.float32), sfreq=128.0,
            labels=["a"] * 22, event_pos=np.array([5]),
            event_typ=np.array([769]))
        with tempfile.TemporaryDirectory() as d:
            p = pr.save(Path(d) / "x-preprocessed.npz")
            back = ProcessedRecording.load(p)
        np.testing.assert_array_equal(back.data, pr.data)
        self.assertEqual(back.sfreq, 128.0)
        np.testing.assert_array_equal(back.event_typ, pr.event_typ)


class TestEpoching(unittest.TestCase):
    def test_map_labels_parity(self):
        y = np.array([7, 8, 9, 10, 7])
        out = map_labels(y, {7: 0, 8: 1, 9: 2, 10: 3})
        np.testing.assert_array_equal(out, [0, 1, 2, 3, 0])
        with self.assertRaises(RuntimeError):
            map_labels(np.array([7, 99]), {7: 0})

    def test_extract_epochs_train(self):
        sfreq = 128.0
        data = np.arange(22 * 2000, dtype=np.float32).reshape(22, 2000)
        pos = np.array([100, 600, 1100, 1900])  # last one runs off the end
        typ = np.array([769, 771, 772, 770])
        X, y, kept = extract_epochs(data, sfreq, pos, typ, mode="Train")
        self.assertEqual(X.shape, (3, 22, 257))
        np.testing.assert_array_equal(y, [0, 2, 3])
        np.testing.assert_array_equal(kept, [0, 1, 2])
        # Window starts 64 samples (0.5 s) after the cue.
        np.testing.assert_array_equal(X[0, 0], data[0, 164:164 + 257])

    def test_extract_epochs_eval_selects_unknown_cues(self):
        data = np.zeros((22, 3000), np.float32)
        pos = np.array([100, 600, 1100])
        typ = np.array([769, CUE_UNKNOWN, CUE_UNKNOWN])
        X, y, kept = extract_epochs(data, 128.0, pos, typ, mode="Eval")
        self.assertEqual(X.shape[0], 2)
        np.testing.assert_array_equal(y, [0, 0])

    def test_unknown_mode_raises(self):
        with self.assertRaises(ValueError):
            extract_epochs(np.zeros((1, 10), np.float32), 128.0,
                           np.zeros(0, int), np.zeros(0, int), mode="Test")


class TestEndToEndDatasetCLI(unittest.TestCase):
    """Synthetic GDF tree -> CLI preprocessing -> loadable trials."""

    def _make_raw_tree(self, root: Path, subjects=(1, 4)):
        from scipy.io import savemat

        rng = np.random.RandomState(0)
        sfreq, secs = 250.0, 40
        n = int(sfreq * secs)
        n_trials = 8
        for s in subjects:
            for mode, code in (("Train", None), ("Eval", CUE_UNKNOWN)):
                sig = rng.uniform(-0.5, 0.5, (25, n)).astype(np.float32)
                pos = (np.arange(n_trials) * 1100 + 300).astype(np.int64)
                if mode == "Train":
                    typ = np.array([769, 770, 771, 772] * 2)
                else:
                    typ = np.full(n_trials, code)
                sess = "T" if mode == "Train" else "E"
                write_gdf(root / mode / f"A{s:02d}{sess}.gdf", sig, sfreq,
                          event_pos=pos, event_typ=typ)
                if mode == "Eval":
                    labels = rng.randint(1, 5, n_trials)
                    tl = root / "TrueLabels"
                    tl.mkdir(parents=True, exist_ok=True)
                    savemat(tl / f"A{s:02d}E.mat", {"classlabel": labels})

    def test_build_processed_tree_and_load(self):
        from eegnetreplication_tpu.data.io import load_subject_dataset
        from eegnetreplication_tpu.dataset import build_processed_tree

        tmp = Path(tempfile.mkdtemp())
        try:
            paths = Paths.from_root(tmp)
            self._make_raw_tree(paths.data_raw)
            build_processed_tree(paths)

            for mode in ("Train", "Eval"):
                d = load_subject_dataset(subject=1, mode=mode, paths=paths)
                self.assertEqual(d.X.shape[1:], (22, 257))
                self.assertEqual(len(d), 8)
                self.assertTrue(set(np.unique(d.y)) <= {0, 1, 2, 3})
            # Subject filter vs all.
            all_train = load_subject_dataset(subject="all", mode="Train",
                                             paths=paths)
            self.assertEqual(len(all_train), 16)

            # Eval labels come from the TrueLabels .mat files.
            true = load_true_labels("A01E", paths)
            d = load_subject_dataset(subject=1, mode="Eval", paths=paths)
            np.testing.assert_array_equal(d.y, true)
        finally:
            shutil.rmtree(tmp)

    def test_loader_errors_without_data(self):
        from eegnetreplication_tpu.data.io import load_subject_dataset

        tmp = Path(tempfile.mkdtemp())
        try:
            with self.assertRaises(FileNotFoundError):
                load_subject_dataset(subject=1, mode="Train",
                                     paths=Paths.from_root(tmp))
        finally:
            shutil.rmtree(tmp)


if __name__ == "__main__":
    unittest.main()
