"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware (the JAX analog of a fake process group; the
reference has no distributed tests at all, SURVEY.md §4).

Note: the environment's site startup pins ``jax_platforms`` to ``axon,cpu``
(tunneled TPU), overriding the ``JAX_PLATFORMS`` env var — so we force CPU via
``jax.config`` before any backend initializes.  Set ``EEGTPU_TEST_TPU=1`` to
run the suite on the real chip instead.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("EEGTPU_NO_LOG_FILE", "1")

if not os.environ.get("EEGTPU_TEST_TPU"):
    import jax

    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """Run the bench selftests last.

    The ``*Selftest*`` legs are minutes-sized end-to-end subprocess
    benches (real serve processes, real SIGKILL); everything else is a
    seconds-sized unit surface.  A budgeted tier-1 run should buy the
    fast feedback first and spend whatever time remains on the
    end-to-end legs, so a timeout truncates the slowest tail instead of
    starving the unit tests queued behind a bench boot.  The reorder is
    stable: relative order within each group is unchanged.
    """
    tail = [it for it in items if "selftest" in it.nodeid.lower()]
    head = [it for it in items if "selftest" not in it.nodeid.lower()]
    items[:] = head + tail


@pytest.fixture(autouse=True)
def _resil_state_isolated():
    """The fault-injection registry, preemption flag, and process-default
    heartbeat emitter are process-global; a test that arms a site,
    requests a stop, or configures a heartbeat file must never leak it
    into the next test."""
    yield
    from eegnetreplication_tpu.resil import heartbeat, inject, preempt

    inject.disarm_all()
    preempt.clear()
    heartbeat.reset_default()
