"""Online inference serving subsystem (``eegnetreplication_tpu/serve/``).

Covers the ISSUE-3 acceptance surface: bucket selection and padding in the
engine, micro-batcher coalescing/scatter-order/backpressure, hot-reload
under concurrent load with zero dropped requests, SIGTERM-shaped drain,
the ``serve.forward`` chaos site under the shared retry policy, the HTTP
boundary, and the ``serve_bench.py --selftest`` tier-1 leg.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from eegnetreplication_tpu.models import EEGNet  # noqa: E402
from eegnetreplication_tpu.obs import journal as obs_journal  # noqa: E402
from eegnetreplication_tpu.serve.batcher import (  # noqa: E402
    MicroBatcher,
    Rejected,
)
from eegnetreplication_tpu.serve.engine import (  # noqa: E402
    InferenceEngine,
    bucket_ladder,
)
from eegnetreplication_tpu.serve.registry import ModelRegistry  # noqa: E402
from eegnetreplication_tpu.training.checkpoint import (  # noqa: E402
    save_checkpoint,
)

REPO = Path(__file__).resolve().parent.parent

C, T = 4, 64


def _variables(seed: int = 0):
    model = EEGNet(n_channels=C, n_times=T)
    variables = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, C, T)),
                           train=False)
    return model, variables["params"], variables["batch_stats"]


@pytest.fixture(scope="module")
def small_engine():
    model, params, bs = _variables()
    return InferenceEngine(model, params, bs, buckets=(1, 4, 16))


@pytest.fixture(scope="module")
def trials():
    return np.random.RandomState(0).randn(40, C, T).astype(np.float32)


def _checkpoint(tmp_path: Path, seed: int = 0, name: str = "m.npz") -> Path:
    model, params, bs = _variables(seed)
    return save_checkpoint(
        tmp_path / name, params, bs,
        metadata={"model": "eegnet", "n_channels": C, "n_times": T,
                  "F1": model.F1, "D": model.D})


class TestEngine:
    def test_bucket_selection_and_ladder(self, small_engine):
        assert [small_engine.bucket_for(n) for n in (1, 2, 4, 5, 16, 99)] \
            == [1, 4, 4, 16, 16, 16]
        assert bucket_ladder(256) == (1, 8, 32, 128, 256)
        assert bucket_ladder(16) == (1, 8, 16)
        assert bucket_ladder(1) == (1,)

    def test_bucket_ladder_edge_cases(self):
        """ISSUE-8 satellite: max_batch equal to / between / just above
        base rungs, and validation."""
        # Equal to a base rung: the rung caps the ladder, no duplicate.
        assert bucket_ladder(8) == (1, 8)
        assert bucket_ladder(32) == (1, 8, 32)
        assert bucket_ladder(128) == (1, 8, 32, 128)
        # Between rungs: cap inserted, larger base rungs dropped.
        assert bucket_ladder(20) == (1, 8, 20)
        assert bucket_ladder(2) == (1, 2)
        # Just above the top base rung: every base rung kept + the cap.
        assert bucket_ladder(129) == (1, 8, 32, 128, 129)
        # Custom base ladders compose the same way.
        assert bucket_ladder(24, base=(1, 16, 64)) == (1, 16, 24)
        with pytest.raises(ValueError, match="max_batch"):
            bucket_ladder(0)

    def test_padded_buckets_match_direct_forward(self, small_engine, trials):
        model, params, bs = (small_engine.model, small_engine.params,
                             small_engine.batch_stats)
        direct = np.argmax(np.asarray(model.apply(
            {"params": params, "batch_stats": bs}, jnp.asarray(trials),
            train=False)), axis=1)
        # Sizes straddling every bucket boundary, incl. chunking > top.
        for n in (1, 3, 4, 5, 16, 17, 40):
            np.testing.assert_array_equal(
                small_engine.infer(trials[:n]), direct[:n])

    def test_empty_and_bad_geometry(self, small_engine):
        assert small_engine.infer(np.zeros((0, C, T), np.float32)).shape \
            == (0,)
        with pytest.raises(ValueError, match="expected trials shaped"):
            small_engine.infer(np.zeros((2, C + 1, T), np.float32))

    def test_warmup_journals_compiles(self, tmp_path):
        with obs_journal.run(tmp_path, config={}) as jr:
            model, params, bs = _variables()
            engine = InferenceEngine(model, params, bs, buckets=(1, 4),
                                     journal=jr)
            walls = engine.warmup()
            assert set(walls) == {1, 4}
            assert engine.warmup() == {}  # idempotent
        events = obs_journal.schema.read_events(jr.events_path)
        whats = [e["what"] for e in events if e["event"] == "compile_end"]
        assert whats == ["serve_forward_b1", "serve_forward_b4"]

    def test_warmup_persistent_cache_replays_on_second_engine(
            self, tmp_path, monkeypatch):
        """ISSUE-6 satellite: with EEGTPU_COMPILE_CACHE set, warmup
        enables the persistent compilation cache (explicit opt-in, CPU
        included) and journals ``compile`` events whose ``cache_hit``
        flips to True once the executables exist — what makes replica
        restarts and scale-out skip recompiles."""
        monkeypatch.setenv("EEGTPU_COMPILE_CACHE", str(tmp_path / "cc"))
        try:
            with obs_journal.run(tmp_path / "obs", config={}) as jr:
                model, params, bs = _variables()
                InferenceEngine(model, params, bs, buckets=(1, 4),
                                journal=jr).warmup()
                # A NEW engine object (fresh jit) over the same program:
                # the persistent cache, not the in-process one, must
                # answer.
                InferenceEngine(model, params, bs, buckets=(1, 4),
                                journal=jr).warmup()
            events = obs_journal.schema.read_events(jr.events_path)
            compiles = [e for e in events if e["event"] == "compile"]
            assert [e["what"] for e in compiles] == [
                "serve_forward_b1", "serve_forward_b4"] * 2
            assert [e["cache_hit"] for e in compiles[:2]] == [False, False]
            assert [e["cache_hit"] for e in compiles[2:]] == [True, True]
            assert all(e["cache_dir"] == str(tmp_path / "cc")
                       for e in compiles)
            assert not any("_schema_error" in e for e in events)
        finally:
            # The cache dir is a pytest tmp path: leaving the global jax
            # config pointed at it would leak into every later test.
            jax.config.update("jax_compilation_cache_dir", None)

    def test_digest_identifies_weights(self, tmp_path):
        a = InferenceEngine.from_checkpoint(_checkpoint(tmp_path, seed=0),
                                            buckets=(1,), warm=False)
        b = InferenceEngine.from_checkpoint(
            _checkpoint(tmp_path, seed=1, name="b.npz"), buckets=(1,),
            warm=False)
        assert a.digest != b.digest
        again = InferenceEngine.from_checkpoint(
            _checkpoint(tmp_path, seed=0, name="a2.npz"), buckets=(1,),
            warm=False)
        assert a.digest == again.digest


class TestBatcher:
    def test_coalesces_and_scatters_in_fifo_order(self):
        calls = []

        def infer(x):
            calls.append(len(x))
            return x[:, 0, 0]  # row fingerprint: scatter is checkable

        b = MicroBatcher(infer, max_batch=16, max_wait_ms=50.0,
                         max_queue_trials=64)
        try:
            xs = [np.full((n, C, T), i, np.float32)
                  for i, n in enumerate((3, 2, 4, 1), start=1)]
            futs = [b.submit(x) for x in xs]
            for i, fut in enumerate(futs, start=1):
                got = fut.result(timeout=10)
                assert got.shape == (len(xs[i - 1]),)
                assert (got == i).all()  # each future got ITS rows
            assert calls and calls[0] >= 5  # first dispatch coalesced
        finally:
            b.close()

    def test_scatter_under_interleaved_concurrent_arrivals(self,
                                                           small_engine):
        # 12 threads race single-trial submits; every response must be the
        # prediction of the submitted trial, regardless of batch mixing.
        x = np.random.RandomState(1).randn(48, C, T).astype(np.float32)
        want = small_engine.infer(x)
        b = MicroBatcher(small_engine.infer, max_batch=8, max_wait_ms=2.0,
                         max_queue_trials=64)
        results = {}
        lock = threading.Lock()

        def client(lo, hi):
            for i in range(lo, hi):
                got = b.submit(x[i][None]).result(timeout=30)
                with lock:
                    results[i] = got[0]

        try:
            threads = [threading.Thread(target=client, args=(k * 4, k * 4 + 4))
                       for k in range(12)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            b.close()
        got = np.array([results[i] for i in range(48)])
        np.testing.assert_array_equal(got, want)

    def test_backpressure_rejects_when_full(self):
        release = threading.Event()

        def slow_infer(x):
            release.wait(10)
            return np.zeros(len(x), np.int64)

        b = MicroBatcher(slow_infer, max_batch=4, max_wait_ms=0.0,
                         max_queue_trials=4)
        try:
            first = b.submit(np.zeros((4, C, T), np.float32))
            time.sleep(0.1)  # let the worker take the first batch
            second = b.submit(np.zeros((4, C, T), np.float32))  # fills queue
            with pytest.raises(Rejected, match="queue full"):
                b.submit(np.zeros((1, C, T), np.float32))
            release.set()
            assert first.result(timeout=10).shape == (4,)
            assert second.result(timeout=10).shape == (4,)
        finally:
            release.set()
            b.close()

    def test_infer_error_fails_only_that_batch(self):
        boom = [True]

        def infer(x):
            if boom[0]:
                boom[0] = False
                raise ValueError("deterministic failure")
            return np.zeros(len(x), np.int64)

        b = MicroBatcher(infer, max_batch=4, max_wait_ms=0.0,
                         max_queue_trials=16)
        try:
            bad = b.submit(np.zeros((2, C, T), np.float32))
            with pytest.raises(ValueError, match="deterministic failure"):
                bad.result(timeout=10)
            ok = b.submit(np.zeros((2, C, T), np.float32))
            assert ok.result(timeout=10).shape == (2,)
        finally:
            b.close()

    def test_close_without_drain_fails_pending(self):
        started = threading.Event()
        release = threading.Event()

        def slow_infer(x):
            started.set()
            release.wait(10)
            return np.zeros(len(x), np.int64)

        b = MicroBatcher(slow_infer, max_batch=1, max_wait_ms=0.0,
                         max_queue_trials=8)
        in_flight = b.submit(np.zeros((1, C, T), np.float32))
        assert started.wait(5)
        queued = b.submit(np.zeros((1, C, T), np.float32))
        threading.Timer(0.05, release.set).start()
        b.close(drain=False)
        with pytest.raises(Rejected, match="shutting down"):
            queued.result(timeout=10)
        assert in_flight.result(timeout=10).shape == (1,)
        with pytest.raises(Rejected):
            b.submit(np.zeros((1, C, T), np.float32))


class TestHotReload:
    def test_reload_under_concurrent_load_drops_nothing(self, tmp_path):
        """ISSUE 3 acceptance: a hot-reload during load completes with
        zero failed requests, and traffic after the swap is answered by
        the new weights."""
        from eegnetreplication_tpu.serve.service import make_infer_fn

        ck_a = _checkpoint(tmp_path, seed=0, name="a.npz")
        ck_b = _checkpoint(tmp_path, seed=1, name="b.npz")
        registry = ModelRegistry(buckets=(1, 4, 16))
        registry.load(ck_a)
        digest_a = registry.engine.digest
        b = MicroBatcher(make_infer_fn(registry), max_batch=16,
                         max_wait_ms=1.0, max_queue_trials=256)
        x = np.random.RandomState(2).randn(8, C, T).astype(np.float32)
        failures = []
        done = [0]
        lock = threading.Lock()

        def client():
            for i in range(40):
                try:
                    b.submit(x[i % len(x)][None]).result(timeout=30)
                except Exception as exc:  # noqa: BLE001 — the assertion
                    with lock:
                        failures.append(repr(exc))
                with lock:
                    done[0] += 1

        threads = [threading.Thread(target=client) for _ in range(6)]
        try:
            for t in threads:
                t.start()
            while done[0] < 60:  # mid-load
                time.sleep(0.005)
            registry.reload(ck_b)
            for t in threads:
                t.join()
        finally:
            b.close()
        assert failures == []
        assert done[0] == 240
        assert registry.swaps == 1
        assert registry.engine.digest != digest_a
        # Post-swap traffic is computed by checkpoint B's weights.
        engine_b = InferenceEngine.from_checkpoint(ck_b, buckets=(1, 4, 16),
                                                   warm=False)
        np.testing.assert_array_equal(registry.infer(x), engine_b.infer(x))

    def test_failed_reload_keeps_serving(self, tmp_path):
        registry = ModelRegistry(buckets=(1,))
        registry.load(_checkpoint(tmp_path))
        digest = registry.engine.digest
        with pytest.raises(FileNotFoundError):
            registry.reload(tmp_path / "missing.npz")
        assert registry.engine.digest == digest
        assert registry.swaps == 0

    def test_reload_rejects_corrupt_checkpoint(self, tmp_path):
        from eegnetreplication_tpu.resil.integrity import IntegrityError

        registry = ModelRegistry(buckets=(1,))
        registry.load(_checkpoint(tmp_path))
        bad = _checkpoint(tmp_path, seed=1, name="bad.npz")
        data = bad.read_bytes()
        bad.write_bytes(data[: len(data) // 2] + b"\x00garbled")
        with pytest.raises(IntegrityError):
            registry.reload(bad)
        assert registry.swaps == 0

    def test_reload_rejects_geometry_change(self, tmp_path):
        """In-flight requests were validated against the live geometry; a
        different-(C,T) push must be refused, not swapped in."""
        from eegnetreplication_tpu.training.checkpoint import save_checkpoint

        registry = ModelRegistry(buckets=(1,))
        registry.load(_checkpoint(tmp_path))
        other = EEGNet(n_channels=C + 2, n_times=T)
        variables = other.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, C + 2, T)), train=False)
        wide = save_checkpoint(
            tmp_path / "wide.npz", variables["params"],
            variables["batch_stats"],
            metadata={"model": "eegnet", "n_channels": C + 2, "n_times": T,
                      "F1": other.F1, "D": other.D})
        with pytest.raises(ValueError, match="geometry mismatch"):
            registry.reload(wide)
        assert registry.swaps == 0
        assert registry.engine.geometry == (C, T)

    def test_swap_journaled(self, tmp_path):
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            registry = ModelRegistry(buckets=(1,), journal=jr)
            registry.load(_checkpoint(tmp_path))
            registry.reload(_checkpoint(tmp_path, seed=1, name="b.npz"))
        events = obs_journal.schema.read_events(jr.events_path)
        swaps = [e for e in events if e["event"] == "model_swap"]
        assert len(swaps) == 1
        assert swaps[0]["digest"] != swaps[0]["previous_digest"]


@pytest.fixture
def serve_app(tmp_path):
    """A live service on an ephemeral port inside a journaled run."""
    from eegnetreplication_tpu.serve.service import ServeApp

    ck = _checkpoint(tmp_path)
    with obs_journal.run(tmp_path / "obs", config={}) as jr:
        app = ServeApp(ck, port=0, buckets=(1, 4, 16), max_wait_ms=1.0,
                       journal=jr).start()
        try:
            yield app, jr, tmp_path
        finally:
            app.stop()


def _post(url: str, payload: dict, timeout: float = 30.0) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    return json.loads(urllib.request.urlopen(req, timeout=timeout).read())


class TestHTTPService:
    def test_predict_healthz_metrics_roundtrip(self, serve_app, trials):
        app, jr, _ = serve_app
        want = app.registry.engine.infer(trials[:5])
        resp = _post(app.url + "/predict", {"trials": trials[:5].tolist()})
        assert resp["predictions"] == [int(p) for p in want]
        assert resp["model_digest"] == app.registry.engine.digest
        health = json.loads(urllib.request.urlopen(
            app.url + "/healthz", timeout=10).read())
        assert health["status"] == "ok"
        assert health["geometry"] == {"n_channels": C, "n_times": T}
        # Fleet-router satellite: the canary-identity digest and live
        # queue depths ride on /healthz — no separate endpoint.
        assert health["variables_digest"] == app.registry.engine.digest
        assert health["queue_depth_trials"] == 0
        assert health["queue_depth_requests"] == 0
        metrics = json.loads(urllib.request.urlopen(
            app.url + "/metrics", timeout=10).read())
        obs_journal.schema.validate_metrics(metrics)
        # Satellite: the batcher publishes LIVE queue-depth gauges (not
        # just per-batch bucket_fill) — the request above must have left
        # them registered and drained back to zero.
        gauges = metrics["gauges"]
        assert gauges["queue_depth_trials"][0]["value"] == 0
        assert gauges["queue_depth_requests"][0]["value"] == 0

    def test_bad_shape_is_400_and_journaled(self, serve_app):
        app, jr, _ = serve_app
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(app.url + "/predict",
                  {"trials": np.zeros((2, C + 3, T)).tolist()})
        assert err.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(app.url + "/predict", {"wrong_key": []})
        assert err.value.code == 400

    def test_reload_endpoint_swaps_model(self, serve_app, tmp_path):
        app, jr, root = serve_app
        ck_b = _checkpoint(root, seed=1, name="b.npz")
        old = app.registry.engine.digest
        resp = _post(app.url + "/reload", {"checkpoint": str(ck_b)},
                     timeout=120)
        assert resp["status"] == "ok"
        assert resp["model_digest"] != old
        health = json.loads(urllib.request.urlopen(
            app.url + "/healthz", timeout=10).read())
        assert health["model_swaps"] == 1

    def test_request_events_and_serve_lifecycle_journaled(self, serve_app,
                                                          trials):
        app, jr, _ = serve_app
        for i in range(3):
            _post(app.url + "/predict", {"trials": trials[i:i + 1].tolist()})
        app.stop()  # flush serve_end before reading the stream
        events = obs_journal.schema.read_events(jr.events_path,
                                                complete=False)
        kinds = [e["event"] for e in events]
        assert "serve_start" in kinds
        requests = [e for e in events if e["event"] == "request"]
        assert len(requests) == 3
        assert all(e["status"] == "ok" for e in requests)
        end = [e for e in events if e["event"] == "serve_end"]
        assert end and end[0]["n_requests"] == 3 and end[0]["rejected"] == 0
        summary = obs_journal.schema.event_summary(events)
        assert summary["n_requests"] == 3
        assert summary["rejected"] == 0
        assert "latency_p95_ms" in summary


class TestDrain:
    def test_preempt_requested_drains_and_journals_serve_end(self, tmp_path,
                                                             trials):
        """SIGTERM-shaped stop: preempt.request() is exactly what the
        guard's signal handler calls; the serve loop must answer every
        accepted request, then close with serve_end."""
        from eegnetreplication_tpu.resil import preempt
        from eegnetreplication_tpu.serve.service import (
            ServeApp,
            serve_until_preempted,
        )

        ck = _checkpoint(tmp_path)
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            app = ServeApp(ck, port=0, buckets=(1, 4, 16), max_wait_ms=1.0,
                           journal=jr).start()
            loop = threading.Thread(
                target=serve_until_preempted, args=(app, 0.01), daemon=True)
            loop.start()
            results = [_post(app.url + "/predict",
                             {"trials": trials[i:i + 1].tolist()})
                       for i in range(4)]
            preempt.request("SIGTERM")
            loop.join(timeout=30)
            assert not loop.is_alive()
        assert all(len(r["predictions"]) == 1 for r in results)
        events = obs_journal.schema.read_events(jr.events_path)
        end = [e for e in events if e["event"] == "serve_end"]
        assert end and end[0]["n_requests"] == 4

    def test_drain_with_queued_requests_keeps_stream_terminal(self,
                                                              tmp_path,
                                                              trials):
        """Stop while handler threads are still blocked on queued work:
        the drained requests' journal events must land BEFORE serve_end /
        run_end (stream stays schema-complete) and be counted in it."""
        from eegnetreplication_tpu.resil import preempt
        from eegnetreplication_tpu.serve.service import (
            ServeApp,
            serve_until_preempted,
        )

        ck = _checkpoint(tmp_path)
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            # A long coalescing window parks the queue so the drain is
            # what resolves these requests, not normal service.
            app = ServeApp(ck, port=0, buckets=(1, 4, 16),
                           max_wait_ms=5000.0, journal=jr).start()
            results = []
            lock = threading.Lock()

            def post(i):
                r = _post(app.url + "/predict",
                          {"trials": trials[i:i + 1].tolist()}, timeout=60)
                with lock:
                    results.append(r)

            posters = [threading.Thread(target=post, args=(i,))
                       for i in range(5)]
            for t in posters:
                t.start()
            time.sleep(0.3)  # requests queued, handlers blocked
            preempt.request("SIGTERM")
            serve_until_preempted(app, poll_s=0.01)
            for t in posters:
                t.join(timeout=30)
        assert len(results) == 5
        # complete=True raises if any request event landed after run_end.
        events = obs_journal.schema.read_events(jr.events_path)
        end = [e for e in events if e["event"] == "serve_end"]
        assert end and end[0]["n_requests"] == 5

    def test_host_preempt_chaos_site_stops_the_loop(self, tmp_path):
        from eegnetreplication_tpu.resil import inject
        from eegnetreplication_tpu.serve.service import (
            ServeApp,
            serve_until_preempted,
        )

        app = ServeApp(_checkpoint(tmp_path), port=0, buckets=(1,))
        app.start()
        inject.arm("host.preempt", times=1)
        t0 = time.perf_counter()
        serve_until_preempted(app, poll_s=0.01)  # returns, doesn't hang
        assert time.perf_counter() - t0 < 10


class TestServeForwardChaos:
    def test_transient_fault_is_retried_and_request_succeeds(self, tmp_path):
        from eegnetreplication_tpu.resil import inject
        from eegnetreplication_tpu.serve.service import make_infer_fn

        registry = ModelRegistry(buckets=(1, 4))
        registry.load(_checkpoint(tmp_path))
        b = MicroBatcher(make_infer_fn(registry), max_batch=4,
                         max_wait_ms=0.0, max_queue_trials=16)
        try:
            # Default serve.forward action: device-fault-shaped -> retried.
            inject.arm("serve.forward", times=1)
            got = b.submit(np.zeros((2, C, T), np.float32)).result(timeout=30)
            assert got.shape == (2,)
        finally:
            b.close()

    def test_fatal_fault_fails_the_batch(self, tmp_path):
        from eegnetreplication_tpu.resil import inject
        from eegnetreplication_tpu.serve.service import make_infer_fn

        registry = ModelRegistry(buckets=(1, 4))
        registry.load(_checkpoint(tmp_path))
        b = MicroBatcher(make_infer_fn(registry), max_batch=4,
                         max_wait_ms=0.0, max_queue_trials=16)
        try:
            inject.arm("serve.forward", times=1, exc="ValueError",
                       message="fatal by classification")
            with pytest.raises(ValueError, match="fatal by classification"):
                b.submit(np.zeros((1, C, T), np.float32)).result(timeout=30)
            # Next batch is clean: the site fired its one time.
            got = b.submit(np.zeros((1, C, T), np.float32)).result(timeout=30)
            assert got.shape == (1,)
        finally:
            b.close()


class TestDeadlines:
    def test_expired_request_dropped_at_dequeue_before_forward(self):
        from eegnetreplication_tpu.serve.batcher import DeadlineExceeded

        release = threading.Event()
        calls = []

        def infer(x):
            calls.append(len(x))
            release.wait(10)
            return np.zeros(len(x), np.int64)

        b = MicroBatcher(infer, max_batch=4, max_wait_ms=0.0,
                         max_queue_trials=16)
        try:
            first = b.submit(np.zeros((1, C, T), np.float32))
            time.sleep(0.1)  # worker took the first batch, now blocked
            expired = b.submit(np.zeros((1, C, T), np.float32),
                               deadline=time.monotonic() - 0.001)
            live = b.submit(np.zeros((1, C, T), np.float32),
                            deadline=time.monotonic() + 60.0)
            release.set()
            with pytest.raises(DeadlineExceeded):
                expired.result(timeout=10)
            assert live.result(timeout=10).shape == (1,)
            assert first.result(timeout=10).shape == (1,)
            # The expired trial never reached a forward: only the first
            # batch and the live request were dispatched.
            assert sum(calls) == 2
        finally:
            release.set()
            b.close()

    def test_http_deadline_header_answers_504(self, serve_app, trials):
        app, jr, _ = serve_app
        req = urllib.request.Request(
            app.url + "/predict",
            data=json.dumps({"trials": trials[:1].tolist()}).encode(),
            headers={"Content-Type": "application/json",
                     "X-Deadline-Ms": "0.001"})
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=30)
        assert err.value.code == 504
        body = json.loads(err.value.read())
        assert "deadline" in body["error"]

    def test_json_deadline_field_within_budget_is_ok(self, serve_app,
                                                    trials):
        app, jr, _ = serve_app
        resp = _post(app.url + "/predict",
                     {"trials": trials[:1].tolist(),
                      "deadline_ms": 60000.0})
        assert len(resp["predictions"]) == 1

    def test_bad_deadline_is_400(self, serve_app, trials):
        app, jr, _ = serve_app
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(app.url + "/predict",
                  {"trials": trials[:1].tolist(), "deadline_ms": -5})
        assert err.value.code == 400

    def test_expired_requests_journaled_and_counted(self, tmp_path,
                                                    trials):
        from eegnetreplication_tpu.serve.service import ServeApp

        ck = _checkpoint(tmp_path)
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            app = ServeApp(ck, port=0, buckets=(1, 4), max_wait_ms=0.0,
                           journal=jr).start()
            try:
                req = urllib.request.Request(
                    app.url + "/predict",
                    data=json.dumps(
                        {"trials": trials[:1].tolist()}).encode(),
                    headers={"Content-Type": "application/json",
                             "X-Deadline-Ms": "0.001"})
                with pytest.raises(urllib.error.HTTPError) as err:
                    urllib.request.urlopen(req, timeout=30)
                assert err.value.code == 504
            finally:
                app.stop()
        events = obs_journal.schema.read_events(jr.events_path)
        statuses = [e["status"] for e in events if e["event"] == "request"]
        assert statuses == ["expired"]
        end = [e for e in events if e["event"] == "serve_end"][0]
        assert end["expired"] == 1 and end["errors"] == 0
        summary = obs_journal.schema.event_summary(events)
        assert summary["expired"] == 1
        assert summary["request_errors"] == 0


class TestCircuitBreakerServing:
    def _app(self, tmp_path, jr, **kw):
        from eegnetreplication_tpu.serve.service import ServeApp

        return ServeApp(_checkpoint(tmp_path), port=0, buckets=(1, 4),
                        max_wait_ms=0.0, journal=jr, **kw).start()

    def _get(self, url):
        try:
            resp = urllib.request.urlopen(url, timeout=10)
            return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as err:
            return err.code, json.loads(err.read())

    def _predict(self, app, x):
        try:
            return 200, _post(app.url + "/predict",
                              {"trials": x.tolist()}, timeout=30)
        except urllib.error.HTTPError as err:
            return err.code, json.loads(err.read())

    def test_open_circuit_503s_without_forward_then_recovers(self, tmp_path,
                                                             trials):
        """ISSUE 5 acceptance: an open circuit answers /predict and
        /healthz with 503 without invoking the forward, and half-open
        probes close it again with zero dropped in-flight requests."""
        from eegnetreplication_tpu.resil import inject

        x = trials[:1]
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            app = self._app(tmp_path, jr, breaker_threshold=2,
                            breaker_reset_s=0.4)
            try:
                # Fatal-classified injected faults: no retry, each request
                # is one failed dispatch; two of them open the breaker.
                inject.arm("serve.forward", times=2, exc="ValueError",
                           message="fatal by classification")
                for _ in range(2):
                    code, _body = self._predict(app, x)
                    assert code == 500
                assert app.breaker.state == "open"
                # Count forwards while the circuit is open: none may run.
                calls = []
                real_infer = app.registry.infer
                app.registry.infer = lambda t: (calls.append(len(t)),
                                                real_infer(t))[-1]
                code, body = self._predict(app, x)
                assert code == 503
                assert body["circuit"] == "open"
                code, health = self._get(app.url + "/healthz")
                assert code == 503
                assert health["status"] == "degraded"
                assert "circuit_open" in health["degraded"]
                assert calls == []  # fast-fail: the forward never ran
                # Cooldown -> half-open probe -> success closes it.
                time.sleep(0.45)
                code, body = self._predict(app, x)
                assert code == 200 and len(body["predictions"]) == 1
                assert app.breaker.state == "closed"
                code, health = self._get(app.url + "/healthz")
                assert code == 200 and health["status"] == "ok"
                assert health["circuit"] == "closed"
            finally:
                app.stop()
        events = obs_journal.schema.read_events(jr.events_path)
        states = [e["state"] for e in events
                  if e["event"] == "circuit_state"]
        assert states == ["open", "half_open", "closed"]
        end = [e for e in events if e["event"] == "serve_end"][0]
        assert end["circuit_open"] == 1 and end["breaker_trips"] == 1
        summary = obs_journal.schema.event_summary(events)
        assert summary["breaker_trips"] == 1
        assert summary["circuit_refusals"] == 1

    def test_expired_half_open_probe_releases_its_slot(self, tmp_path,
                                                       trials):
        """A probe request shed at dequeue (deadline expired) never
        reaches the forward, so the breaker sees no outcome — the probe
        slot must be released anyway or half-open wedges shut forever."""
        from eegnetreplication_tpu.resil import inject

        x = trials[:1]
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            app = self._app(tmp_path, jr, breaker_threshold=1,
                            breaker_reset_s=0.2)
            try:
                inject.arm("serve.forward", times=1, exc="ValueError",
                           message="fatal by classification")
                assert self._predict(app, x)[0] == 500  # opens
                time.sleep(0.25)  # cooldown: half-open on next allow()
                req = urllib.request.Request(
                    app.url + "/predict",
                    data=json.dumps({"trials": x.tolist()}).encode(),
                    headers={"Content-Type": "application/json",
                             "X-Deadline-Ms": "0.001"})
                with pytest.raises(urllib.error.HTTPError) as err:
                    urllib.request.urlopen(req, timeout=30)
                assert err.value.code == 504  # probe shed at dequeue
                # The slot came back: the next probe runs and closes it.
                code, _body = self._predict(app, x)
                assert code == 200
                assert app.breaker.state == "closed"
            finally:
                app.stop()

    def test_half_open_probe_failure_reopens(self, tmp_path, trials):
        from eegnetreplication_tpu.resil import inject

        x = trials[:1]
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            app = self._app(tmp_path, jr, breaker_threshold=1,
                            breaker_reset_s=0.2)
            try:
                inject.arm("serve.forward", times=2, exc="ValueError",
                           message="fatal by classification")
                assert self._predict(app, x)[0] == 500  # opens
                assert app.breaker.state == "open"
                time.sleep(0.25)
                assert self._predict(app, x)[0] == 500  # probe fails
                assert app.breaker.state == "open"      # re-opened
                time.sleep(0.25)
                assert self._predict(app, x)[0] == 200  # probe succeeds
                assert app.breaker.state == "closed"
            finally:
                app.stop()


class TestHealthzLiveness:
    def test_healthz_reports_worker_heartbeat_fields(self, serve_app):
        app, jr, _ = serve_app
        health = json.loads(urllib.request.urlopen(
            app.url + "/healthz", timeout=10).read())
        assert health["status"] == "ok" and health["degraded"] == []
        assert health["circuit"] == "closed"
        hb = health["worker_heartbeat"]
        assert hb["stale"] is False
        assert hb["phase"] in ("serve_idle", "serve_forward")
        assert hb["age_s"] >= 0.0 and hb["threshold_s"] > 0.0

    def test_healthz_degrades_while_worker_hangs(self, tmp_path, trials):
        from eegnetreplication_tpu.resil import inject
        from eegnetreplication_tpu.serve.service import ServeApp

        ck = _checkpoint(tmp_path)
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            app = ServeApp(ck, port=0, buckets=(1, 4), max_wait_ms=0.0,
                           journal=jr,
                           watchdog_thresholds={"serve_forward": 0.2,
                                                "serve_idle": 10.0}
                           ).start()
            try:
                inject.arm("serve.hang", times=1, sleep=1.5)
                poster = threading.Thread(
                    target=lambda: _post(app.url + "/predict",
                                         {"trials": trials[:1].tolist()},
                                         timeout=30))
                poster.start()
                time.sleep(0.8)  # worker is asleep inside the dispatch
                try:
                    urllib.request.urlopen(app.url + "/healthz", timeout=10)
                    raise AssertionError("healthz did not degrade")
                except urllib.error.HTTPError as err:
                    assert err.code == 503
                    health = json.loads(err.read())
                assert "worker_heartbeat_stale" in health["degraded"]
                assert health["worker_heartbeat"]["phase"] \
                    == "serve_forward"
                poster.join(timeout=30)
                # Worker recovered: beats resumed, healthz back to 200.
                health = json.loads(urllib.request.urlopen(
                    app.url + "/healthz", timeout=10).read())
                assert health["status"] == "ok"
            finally:
                app.stop()

    def test_metrics_body_counts_requests(self, serve_app, trials):
        app, jr, _ = serve_app
        for i in range(2):
            _post(app.url + "/predict", {"trials": trials[i:i + 1].tolist()})
        metrics = json.loads(urllib.request.urlopen(
            app.url + "/metrics", timeout=10).read())
        obs_journal.schema.validate_metrics(metrics)
        ok = [s for s in metrics["counters"]["requests_total"]
              if s["labels"].get("status") == "ok"]
        assert ok and ok[0]["value"] >= 2
        lat = metrics["histograms"]["request_latency_ms"][0]
        assert lat["count"] >= 2 and lat["min"] > 0.0


class TestPredictCLIIntegration:
    def test_predict_trials_routes_through_engine_buckets(self, trials):
        """The CLI path and a server engine agree exactly (shared code)."""
        from eegnetreplication_tpu.predict import predict_trials

        model, params, bs = _variables()
        engine = InferenceEngine(model, params, bs, buckets=(1, 4, 16))
        np.testing.assert_array_equal(
            predict_trials(model, params, bs, trials, batch_size=16),
            engine.infer(trials))

    def test_load_model_back_compat_reexport(self):
        from eegnetreplication_tpu import predict, serve

        assert (predict.load_model_from_checkpoint
                is serve.load_model_from_checkpoint)


class TestBatcherGreedyCoalescing:
    def test_full_bucket_behind_small_head_dispatches_greedily(self):
        """ISSUE-8 regression (full-bucket-behind-small-head arrival
        order): a request too large to join the current batch must not
        stall coalescing — later requests that DO fit ride along, so the
        head batch leaves as a full bucket instead of a tiny forward."""
        first_started = threading.Event()
        release = threading.Event()
        sizes = []

        def infer(x):
            sizes.append(len(x))
            if len(sizes) == 1:  # only the blocker batch parks
                first_started.set()
                release.wait(10)
            return x[:, 0, 0]

        b = MicroBatcher(infer, max_batch=32, max_wait_ms=0.0,
                         max_queue_trials=256)
        try:
            blocker = b.submit(np.full((1, C, T), 9, np.float32))
            assert first_started.wait(5)  # worker holds the blocker batch
            futs = [b.submit(np.full((n, C, T), i, np.float32))
                    for i, n in enumerate((4, 30, 28), start=1)]
            release.set()  # finish blocker; next coalesce sees all three
            got = [f.result(timeout=10) for f in (blocker, *futs)]
            # Greedy: [4, skip 30, 28] coalesces to one FULL bucket of
            # 32; the 30 dispatches next.  Pre-fix behavior was [4], 30,
            # 28 — three underfilled forwards.
            assert sizes == [1, 32, 30], sizes
            # Scatter correctness survives the reorder: each future got
            # its own rows.
            for i, fut in enumerate(futs, start=1):
                assert (got[i] == i).all()
        finally:
            release.set()
            b.close()

    def test_full_bucket_behind_small_head_does_not_wait_out_window(self):
        """With a full top bucket already queued behind a small head, the
        worker must dispatch NOW, not park for max_wait_ms."""
        release = threading.Event()

        def infer(x):
            release.wait(10)
            return np.zeros(len(x), np.int64)

        b = MicroBatcher(infer, max_batch=32, max_wait_ms=5000.0,
                         max_queue_trials=256)
        try:
            small = b.submit(np.zeros((1, C, T), np.float32))
            big = b.submit(np.zeros((32, C, T), np.float32))
            release.set()
            t0 = time.perf_counter()
            assert small.result(timeout=10).shape == (1,)
            assert big.result(timeout=10).shape == (32,)
            assert time.perf_counter() - t0 < 2.0  # far below max_wait
        finally:
            release.set()
            b.close()

    def test_reconfigure_live(self):
        b = MicroBatcher(lambda x: np.zeros(len(x), np.int64),
                         max_batch=8, max_wait_ms=5.0, max_queue_trials=32)
        try:
            b.reconfigure(max_batch=16, max_wait_ms=1.0)
            assert b.max_batch == 16 and b.max_wait_s == 0.001
            # Clamped to the queue bound (constructor invariant).
            b.reconfigure(max_batch=1000)
            assert b.max_batch == 32
            with pytest.raises(ValueError):
                b.reconfigure(max_batch=0)
            with pytest.raises(ValueError):
                b.reconfigure(max_wait_ms=-1.0)
            # Still serving after reconfigure.
            assert b.submit(np.zeros((2, C, T), np.float32)) \
                .result(timeout=10).shape == (2,)
        finally:
            b.close()


class TestLadderTuner:
    def _stats(self, **kw):
        from eegnetreplication_tpu.serve.tuner import LadderStats

        base = dict(window_s=10.0, dispatches=100, trials=1600.0,
                    bucket_counts={}, bucket_fill_mean={})
        base.update(kw)
        return LadderStats(**base)

    def test_propose_grows_saturated_top(self):
        from eegnetreplication_tpu.serve.tuner import propose

        stats = self._stats(trials=3200.0,
                            bucket_counts={16: 80, 1: 20},
                            bucket_fill_mean={16: 0.97, 1: 1.0})
        prop = propose(stats, (1, 4, 16), 5.0)
        assert prop is not None
        assert prop.buckets == (1, 4, 16, 32)
        assert "top_saturated" in prop.reason

    def test_propose_inserts_rung_for_underfilled_top(self):
        from eegnetreplication_tpu.serve.tuner import propose

        stats = self._stats(trials=480.0,
                            bucket_counts={16: 60, 1: 40},
                            bucket_fill_mean={16: 0.3, 1: 1.0})
        prop = propose(stats, (1, 4, 16), 5.0)
        assert prop is not None
        assert 8 in prop.buckets  # next_pow2(0.3 * 16) = 8
        assert "top_underfilled" in prop.reason

    def test_propose_adapts_wait_to_arrival_rate(self):
        from eegnetreplication_tpu.serve.tuner import propose

        # 16000 trials/s vs a 50 ms window: half a 16-bucket arrives in
        # 0.5 ms — the window should shrink hard.
        stats = self._stats(window_s=1.0, trials=16000.0,
                            bucket_counts={16: 100},
                            bucket_fill_mean={16: 0.8})
        prop = propose(stats, (1, 4, 16), 50.0)
        assert prop is not None
        assert "wait_adapted" in prop.reason
        assert prop.max_wait_ms < 50.0

    def test_propose_needs_evidence_and_respects_caps(self):
        from eegnetreplication_tpu.serve.tuner import propose

        thin = self._stats(dispatches=3, trials=48.0,
                           bucket_counts={16: 3},
                           bucket_fill_mean={16: 1.0})
        assert propose(thin, (1, 4, 16), 5.0) is None
        # Saturated top at the cap: no growth proposed.
        capped = self._stats(trials=3200.0, bucket_counts={16: 100},
                             bucket_fill_mean={16: 1.0})
        prop = propose(capped, (1, 4, 16), 5.0, max_top=16)
        assert prop is None or prop.buckets[-1] == 16

    def test_propose_prunes_to_max_rungs(self):
        from eegnetreplication_tpu.serve.tuner import propose

        stats = self._stats(trials=6400.0,
                            bucket_counts={32: 90, 1: 5, 2: 5},
                            bucket_fill_mean={32: 0.95, 1: 1.0, 2: 1.0})
        prop = propose(stats, (1, 2, 4, 8, 32), 2.0, max_rungs=5)
        assert prop is not None
        assert len(prop.buckets) <= 5
        assert prop.buckets[0] == 1 and prop.buckets[-1] == 64

    def test_collect_diffs_metric_windows(self, tmp_path):
        from eegnetreplication_tpu.serve.tuner import LadderTuner

        with obs_journal.run(tmp_path, config={}) as jr:
            tuner = LadderTuner(registry=None, batcher=None, journal=jr)
            for _ in range(4):
                jr.metrics.observe("bucket_fill", 0.5, bucket="16")
                jr.metrics.observe("batch_trials", 8)
            stats = tuner.collect()
            assert stats.dispatches == 4
            assert stats.trials == 32.0
            assert stats.bucket_fill_mean[16] == pytest.approx(0.5)
            # Second window: nothing new happened.
            stats2 = tuner.collect()
            assert stats2.dispatches == 0

    def test_wait_only_proposal_skips_engine_rebuild(self, tmp_path):
        """A proposal that only moves max_wait_ms must not recompile the
        ladder or clobber a caller-set coalescing cap below the top."""
        from eegnetreplication_tpu.serve.service import make_infer_fn
        from eegnetreplication_tpu.serve.tuner import LadderTuner, Proposal

        registry = ModelRegistry(buckets=(1, 4, 16))
        registry.load(_checkpoint(tmp_path), warm=False)
        b = MicroBatcher(make_infer_fn(registry), max_batch=4,
                         max_wait_ms=1.0, max_queue_trials=64)
        try:
            tuner = LadderTuner(registry, b)
            engine_before = registry.engine
            tuner.apply(Proposal(buckets=(1, 4, 16), max_wait_ms=9.0,
                                 reason="wait_adapted"))
            assert registry.engine is engine_before  # no rebuild
            assert registry.retunes == 0
            assert tuner.retunes == 1  # still counted as applied
            assert b.max_batch == 4    # caller cap preserved
            assert b.max_wait_s == 0.009
        finally:
            b.close()

    def test_retune_under_concurrent_infer_drops_nothing(self, tmp_path):
        """ISSUE-8 acceptance: a LadderTuner retune under live load
        completes with zero dropped/failed requests, swaps the ladder
        atomically, and journals ladder_retune."""
        from eegnetreplication_tpu.serve.service import make_infer_fn
        from eegnetreplication_tpu.serve.tuner import LadderTuner, Proposal

        ck = _checkpoint(tmp_path)
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            registry = ModelRegistry(buckets=(1, 4, 16), journal=jr)
            registry.load(ck)
            b = MicroBatcher(make_infer_fn(registry), max_batch=16,
                             max_wait_ms=1.0, max_queue_trials=256,
                             journal=jr)
            tuner = LadderTuner(registry, b, journal=jr)
            x = np.random.RandomState(5).randn(8, C, T).astype(np.float32)
            failures = []
            done = [0]
            lock = threading.Lock()

            def client():
                for i in range(40):
                    try:
                        b.submit(x[i % len(x)][None]).result(timeout=30)
                    except Exception as exc:  # noqa: BLE001 — the assertion
                        with lock:
                            failures.append(repr(exc))
                    with lock:
                        done[0] += 1

            threads = [threading.Thread(target=client) for _ in range(6)]
            try:
                for t in threads:
                    t.start()
                while done[0] < 60:  # mid-load
                    time.sleep(0.005)
                tuner.apply(Proposal(buckets=(1, 4, 8, 16),
                                     max_wait_ms=2.0, reason="test"))
                for t in threads:
                    t.join()
            finally:
                b.close()
            assert failures == []
            assert done[0] == 240
            assert registry.retunes == 1
            assert registry.engine.buckets == (1, 4, 8, 16)
            assert b.max_batch == 16 and b.max_wait_s == 0.002
        events = obs_journal.schema.read_events(jr.events_path)
        retunes = [e for e in events if e["event"] == "ladder_retune"]
        assert len(retunes) == 1
        assert retunes[0]["old_buckets"] == [1, 4, 16]
        assert retunes[0]["new_buckets"] == [1, 4, 8, 16]
        summary = obs_journal.schema.event_summary(events)
        assert summary.get("ladder_retunes") is None  # no serve stream
        assert not any("_schema_error" in e for e in events)


class TestQuantizedServing:
    def test_registry_int8_gate_pass_serves_int8(self, tmp_path, trials):
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            reg8 = ModelRegistry(buckets=(1, 4, 16), precision="int8",
                                 journal=jr)
            reg8.load(_checkpoint(tmp_path))
            reg32 = ModelRegistry(buckets=(1, 4, 16), journal=jr)
            reg32.load(_checkpoint(tmp_path, name="m2.npz"))
            assert reg8.serving_precision == "int8"
            assert reg8.last_gate is not None and reg8.last_gate.passed
            assert reg8.engine.quantized_digest is not None
            assert reg8.engine.digest == reg32.engine.digest  # identity
            agree = float(np.mean(
                reg8.infer(trials) == reg32.infer(trials)))
            assert agree >= 0.99
        events = obs_journal.schema.read_events(jr.events_path)
        gates = [e for e in events if e["event"] == "quant_gate"]
        assert len(gates) == 1
        assert gates[0]["outcome"] == "pass"
        assert gates[0]["agreement"] >= 0.99
        assert not any("_schema_error" in e for e in events)

    def test_gate_refusal_falls_back_to_fp32(self, tmp_path, trials,
                                             monkeypatch):
        """Refuse-and-keep-serving: a quantization that breaks argmax is
        refused by the gate, the registry serves fp32, and the refusal is
        journaled — same shape as the hot-reload integrity gate."""
        from eegnetreplication_tpu.ops import quant

        real_forward = quant.quantized_eval_forward

        def broken_forward(model, qparams, batch_stats, x):
            # A quantization bug that rotates every prediction by one
            # class: guaranteed full disagreement with fp32.
            return jnp.roll(real_forward(model, qparams, batch_stats, x),
                            1, axis=-1)

        monkeypatch.setattr(quant, "quantized_eval_forward",
                            broken_forward)
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            reg = ModelRegistry(buckets=(1, 4), precision="int8",
                                journal=jr)
            reg.load(_checkpoint(tmp_path))
            assert reg.precision == "int8"          # requested
            assert reg.serving_precision == "fp32"  # gate refused
            assert reg.last_gate is not None
            assert reg.last_gate.outcome == "refused"
            # Still answers correctly (on the fp32 engine).
            assert reg.infer(trials[:3]).shape == (3,)
        events = obs_journal.schema.read_events(jr.events_path)
        gates = [e for e in events if e["event"] == "quant_gate"]
        assert gates and gates[0]["outcome"] == "refused"

    def test_healthz_reports_precision_and_active_ladder(self, tmp_path,
                                                         trials):
        from eegnetreplication_tpu.serve.service import ServeApp
        from eegnetreplication_tpu.serve.tuner import Proposal

        ck = _checkpoint(tmp_path)
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            app = ServeApp(ck, port=0, buckets=(1, 4, 16), max_wait_ms=1.0,
                           precision="int8", tune_every_s=3600.0,
                           journal=jr).start()
            try:
                health = json.loads(urllib.request.urlopen(
                    app.url + "/healthz", timeout=10).read())
                assert health["precision"] == "int8"
                assert health["requested_precision"] == "int8"
                assert health["buckets"] == [1, 4, 16]
                assert health["ladder_retunes"] == 0
                assert health["max_batch"] == 16
                assert health["max_wait_ms"] == pytest.approx(1.0)
                # A retune moves the ACTIVE ladder /healthz reports.
                app.tuner.apply(Proposal(buckets=(1, 8, 16),
                                         max_wait_ms=2.5, reason="test"))
                health = json.loads(urllib.request.urlopen(
                    app.url + "/healthz", timeout=10).read())
                assert health["buckets"] == [1, 8, 16]
                assert health["ladder_retunes"] == 1
                assert health["max_wait_ms"] == pytest.approx(2.5)
                # Traffic still flows on the retuned int8 engine.
                resp = _post(app.url + "/predict",
                             {"trials": trials[:2].tolist()})
                assert len(resp["predictions"]) == 2
            finally:
                app.stop()
        events = obs_journal.schema.read_events(jr.events_path)
        end = [e for e in events if e["event"] == "serve_end"][0]
        assert end["ladder_retunes"] == 1
        assert end["precision"] == "int8"
        summary = obs_journal.schema.event_summary(events)
        assert summary["precision"] == "int8"
        assert summary["ladder_retunes"] == 1
        assert summary["quant_gate"] == "pass"

    def test_unknown_precision_is_an_error_not_int8(self):
        """A typo'd precision must raise, not silently quantize."""
        from eegnetreplication_tpu.serve.engine import build_gated_engine

        model, params, bs = _variables()
        with pytest.raises(ValueError, match="precision"):
            build_gated_engine(model, params, bs, (1, 4),
                               precision="fp16", warm=False)
        with pytest.raises(ValueError, match="precision"):
            InferenceEngine(model, params, bs, buckets=(1,),
                            precision="INT8")

    def test_predict_trials_precision_routes_through_gated_engine(
            self, trials):
        """ISSUE-8 satellite: the CLI path and the server build the int8
        engine through the same gate, so their predictions agree."""
        from eegnetreplication_tpu.predict import predict_trials
        from eegnetreplication_tpu.serve.engine import build_gated_engine

        model, params, bs = _variables()
        engine, gate = build_gated_engine(model, params, bs, (1, 4, 16),
                                          precision="int8", warm=False)
        assert gate is not None
        np.testing.assert_array_equal(
            predict_trials(model, params, bs, trials, batch_size=16,
                           precision="int8"),
            engine.infer(trials))


class TestServeBenchSelftest:
    def test_selftest_passes(self, tmp_path):
        """Tier-1 acceptance leg: dynamic batching beats sequential by the
        ISSUE floor and a hot-reload under load drops nothing."""
        out = tmp_path / "BENCH_SERVE_selftest.json"
        trace_out = tmp_path / "BENCH_TRACE_selftest.json"
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "serve_bench.py"),
             "--selftest", "--out", str(out),
             "--traceOut", str(trace_out)],
            capture_output=True, text=True, timeout=600,
            env=dict(os.environ, EEGTPU_NO_LOG_FILE="1",
                     EEGTPU_PLATFORM="cpu"))
        assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
        assert "SELFTEST PASS" in proc.stdout
        record = json.loads(out.read_text())
        assert record["bucket32_speedup"] >= 3.0
        assert record["batching_speedup"] >= 3.0
        assert record["open_loop"]["failures"] == 0
        assert record["swap_leg"]["failures"] == 0
        assert record["http_smoke"]["ok"] is True
        assert record["model_swaps"] >= 1
        # ISSUE-9: tracing at 10% sampling keeps >= 0.95x the untraced
        # rps, and one sampled request stitches router -> queue ->
        # forward -> scatter across the two process journals.
        trace_record = json.loads(trace_out.read_text())
        assert trace_record["overhead_ratio"] >= 0.95
        assert trace_record["stitched"]["ok"] is True
        assert trace_record["stitched"]["complete_traces"] >= 1


@pytest.mark.slow
class TestServeBenchFull:
    def test_full_load_generator(self, tmp_path):
        """The full-size load generator (reference geometry, thousands of
        requests) — the BENCH_SERVE.json producer, excluded from tier-1."""
        out = tmp_path / "BENCH_SERVE.json"
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "serve_bench.py"),
             "--out", str(out), "--requests", "1000",
             "--seqRequests", "100"],
            capture_output=True, text=True, timeout=1800,
            env=dict(os.environ, EEGTPU_NO_LOG_FILE="1",
                     EEGTPU_PLATFORM="cpu"))
        assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
        record = json.loads(out.read_text())
        assert record["open_loop"]["failures"] == 0
        assert record["closed_loop"]["failures"] == 0
        # The ISSUE acceptance ratio (bucket-32 vs sequential batch-1)
        # holds at full geometry; the end-to-end open-loop ratio pays
        # per-request Python overhead on top, so its floor is the looser
        # sanity bound (measured ~2.8x at 22x257 on this host).
        assert record["bucket32_speedup"] >= 3.0
        assert record["batching_speedup"] >= 2.0


class TestTracingServing:
    """PR 9: request-scoped tracing through the serving path — spans land
    in the journal, propagate over headers, and flush on anomalies."""

    def _traced_app(self, tmp_path, jr, **kw):
        from eegnetreplication_tpu.serve.service import ServeApp

        return ServeApp(_checkpoint(tmp_path), port=0, buckets=(1, 4),
                        max_wait_ms=0.0, journal=jr, **kw).start()

    def _spans(self, jr, complete=True):
        events = obs_journal.schema.read_events(jr.events_path,
                                                complete=complete)
        return [e for e in events if e["event"] == "span"]

    def test_sampled_request_emits_full_span_chain(self, tmp_path, trials):
        from eegnetreplication_tpu.obs import trace

        x = trials[:2]
        trace_id = trace.new_trace_id()
        parent = trace.new_span_id()
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            app = self._traced_app(tmp_path, jr, trace_sample=0.0)
            try:
                req = urllib.request.Request(
                    app.url + "/predict",
                    data=json.dumps({"trials": x.tolist()}).encode(),
                    headers={"Content-Type": "application/json",
                             trace.TRACE_HEADER: trace_id,
                             trace.PARENT_HEADER: parent,
                             trace.SAMPLED_HEADER: "1"})
                body = json.loads(
                    urllib.request.urlopen(req, timeout=30).read())
                assert len(body["predictions"]) == 2
            finally:
                app.stop()
        spans = self._spans(jr)
        by_name = {s["name"]: s for s in spans}
        for name in ("replica.request", "http.parse", "queue.wait",
                     "batch.forward", "engine.forward", "batch.scatter"):
            assert name in by_name, (name, sorted(by_name))
            assert by_name[name]["trace_id"] == trace_id
        # Cross-process parentage: the replica root hangs off the span id
        # the upstream edge sent in X-Parent-Span.
        assert by_name["replica.request"]["parent_span_id"] == parent
        assert by_name["http.parse"]["parent_span_id"] \
            == by_name["replica.request"]["span_id"]
        assert by_name["engine.forward"]["parent_span_id"] \
            == by_name["batch.forward"]["span_id"]
        assert by_name["engine.forward"]["bucket"] == 4
        assert by_name["engine.forward"]["precision"] == "fp32"
        assert by_name["batch.scatter"]["link_span"] \
            == by_name["batch.forward"]["span_id"]
        summary = obs_journal.schema.event_summary(
            obs_journal.schema.read_events(jr.events_path))
        assert summary["traces"] == 1
        assert not any("_schema_error" in s for s in spans)

    def test_unsampled_ok_request_journals_no_spans(self, tmp_path,
                                                    trials):
        from eegnetreplication_tpu.obs import trace

        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            app = self._traced_app(tmp_path, jr, trace_sample=0.0)
            try:
                req = urllib.request.Request(
                    app.url + "/predict",
                    data=json.dumps({"trials": trials[:1].tolist()}
                                    ).encode(),
                    headers={"Content-Type": "application/json",
                             trace.TRACE_HEADER: trace.new_trace_id(),
                             trace.SAMPLED_HEADER: "0"})
                urllib.request.urlopen(req, timeout=30).read()
            finally:
                app.stop()
        assert self._spans(jr) == []

    def test_unsampled_error_flushes_buffered_spans(self, tmp_path,
                                                    trials):
        """Anomaly tail-capture: an UNSAMPLED trace whose forward fails
        still lands its spans in the journal."""
        from eegnetreplication_tpu.obs import trace
        from eegnetreplication_tpu.resil import inject

        trace_id = trace.new_trace_id()
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            app = self._traced_app(tmp_path, jr, trace_sample=0.0)
            try:
                inject.arm("serve.forward", times=1, exc="ValueError",
                           message="fatal by classification")
                req = urllib.request.Request(
                    app.url + "/predict",
                    data=json.dumps({"trials": trials[:1].tolist()}
                                    ).encode(),
                    headers={"Content-Type": "application/json",
                             trace.TRACE_HEADER: trace_id,
                             trace.SAMPLED_HEADER: "0"})
                with pytest.raises(urllib.error.HTTPError) as err:
                    urllib.request.urlopen(req, timeout=30)
                assert err.value.code == 500
            finally:
                app.stop()
        spans = self._spans(jr)
        assert spans, "anomalous request left no spans"
        assert {s["trace_id"] for s in spans} == {trace_id}
        names = {s["name"] for s in spans}
        assert "queue.wait" in names and "batch.forward" in names
        assert any(s.get("status") == "error" for s in spans)


class TestPrometheusServing:
    def test_metrics_content_negotiation(self, serve_app, trials):
        app, jr, _ = serve_app
        _post(app.url + "/predict", {"trials": trials[:1].tolist()})
        # Default stays the schema-valid JSON snapshot.
        default = json.loads(urllib.request.urlopen(
            app.url + "/metrics", timeout=10).read())
        obs_journal.schema.validate_metrics(default)
        # A scraper's Accept header selects the text exposition format.
        req = urllib.request.Request(
            app.url + "/metrics",
            headers={"Accept": "text/plain; version=0.0.4"})
        resp = urllib.request.urlopen(req, timeout=10)
        assert resp.headers["Content-Type"].startswith("text/plain")
        text = resp.read().decode()
        assert "# TYPE requests_total counter" in text
        assert 'requests_total{status="ok"}' in text
        assert "request_latency_ms_bucket" in text
        assert 'request_latency_ms_bucket{le="+Inf"}' in text

    def test_registry_p95_agrees_with_journal_within_bucket(self, tmp_path,
                                                            trials):
        """ISSUE-9 acceptance: the live bucketed histogram's p95 and the
        journal-derived p95 agree within one bucket width."""
        import bisect

        from eegnetreplication_tpu.obs.metrics import DEFAULT_BUCKET_BOUNDS
        from eegnetreplication_tpu.obs.stats import percentile
        from eegnetreplication_tpu.serve.service import ServeApp

        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            app = ServeApp(_checkpoint(tmp_path), port=0, buckets=(1, 4),
                           max_wait_ms=0.0, journal=jr).start()
            try:
                for _ in range(60):
                    _post(app.url + "/predict",
                          {"trials": trials[:1].tolist()})
                registry_p95 = jr.metrics.quantile("request_latency_ms",
                                                   0.95)
            finally:
                app.stop()
        events = obs_journal.schema.read_events(jr.events_path)
        lat = [e["latency_ms"] for e in events if e["event"] == "request"
               and e["status"] == "ok"]
        assert len(lat) == 60
        journal_p95 = percentile(lat, 0.95)
        bounds = list(DEFAULT_BUCKET_BOUNDS)
        i = bisect.bisect_left(bounds, journal_p95)
        lo = bounds[i - 1] if i > 0 else 0.0
        hi = bounds[i] if i < len(bounds) else max(lat)
        assert lo * 0.999 <= registry_p95 <= hi * 1.001, \
            (registry_p95, journal_p95, lo, hi)
        summary = obs_journal.schema.event_summary(events)
        # event_summary rounds to 3 decimals; same estimator otherwise.
        assert summary["latency_p95_ms"] == round(journal_p95, 3)


class TestSLOServing:
    def test_breach_degrades_healthz_and_recovers(self, tmp_path, trials):
        """ISSUE-9 acceptance: injected serve.forward faults breach the
        error-rate SLO (journaled, healthz degraded); once the fault
        clears and the bad window slides out, the SLO recovers."""
        from eegnetreplication_tpu.resil import inject
        from eegnetreplication_tpu.serve.service import ServeApp

        x = trials[:1]
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            app = ServeApp(_checkpoint(tmp_path), port=0, buckets=(1, 4),
                           max_wait_ms=0.0, journal=jr,
                           slo_spec="error_rate<0.5,availability>0.5",
                           slo_window_s=0.5,
                           slo_interval_s=0.0,  # healthz drives evaluation
                           breaker_threshold=100).start()
            try:
                def get_health():
                    try:
                        resp = urllib.request.urlopen(
                            app.url + "/healthz", timeout=10)
                        return resp.status, json.loads(resp.read())
                    except urllib.error.HTTPError as err:
                        return err.code, json.loads(err.read())

                def predict_once():
                    try:
                        _post(app.url + "/predict", {"trials": x.tolist()})
                        return 200
                    except urllib.error.HTTPError as err:
                        err.read()
                        return err.code

                code, health = get_health()
                assert code == 200 and health["slo"]["breached"] == []
                # Fatal-classified faults: every predict fails.
                inject.arm("serve.forward", times=4, exc="ValueError",
                           message="fatal by classification")
                assert [predict_once() for _ in range(4)] == [500] * 4
                code, health = get_health()
                assert code == 503
                assert "slo:error_rate<0.5" in health["degraded"]
                assert "slo:availability>0.5" in health["degraded"]
                assert set(health["slo"]["breached"]) == {
                    "error_rate<0.5", "availability>0.5"}
                # Fault cleared: healthy traffic ages the breach out of
                # the sliding window.
                deadline = time.monotonic() + 10.0
                code = None
                while time.monotonic() < deadline:
                    assert predict_once() == 200
                    time.sleep(0.15)
                    code, health = get_health()
                    if code == 200:
                        break
                assert code == 200, health
                assert health["slo"]["breached"] == []
                assert health["latency_ms"]["p95"] is not None
            finally:
                app.stop()
        events = obs_journal.schema.read_events(jr.events_path)
        kinds = [e["event"] for e in events if e["event"].startswith("slo_")]
        assert "slo_breach" in kinds and "slo_recovered" in kinds
        # Every breached objective recovered before shutdown.
        summary = obs_journal.schema.event_summary(events)
        assert summary["slo_breached_now"] == []
        assert summary["slo_breaches"] >= 2
        end = [e for e in events if e["event"] == "serve_end"][0]
        assert end["slo_breaches"] >= 2
        assert not any("_schema_error" in e for e in events)
