"""Elastic autoscaling control plane (``serve/fleet/autoscaler.py``).

Covers the ISSUE-17 unit surface: the SLO-driven control law (capacity
estimation, hysteresis bands, cooldowns, max-step), the journaled
``fleet_scale`` decision stream and its drain-safety ordering proof,
membership-truth resync (journal advisory, never authoritative),
stillborn-join reaping, dynamic fleet membership (atomic add/remove,
pinned-drain poll exemption), the router-edge ``ArrivalWindow``, and the
observability fold (``event_summary`` counters, ``FleetState`` scale
column, ``eegtpu-top`` rendering).

Everything here is deterministic: real ``FleetMembership``/``Replica``
state machines with the health poller never started, a fake scaler seam,
and an injectable clock — the autoscaler's ``tick()`` is public exactly
so the loop can be driven without threads.  The end-to-end truth (real
processes, SIGKILL, paced ramp) lives in ``serve_bench.py --scale`` and
the ``fleet.*`` chaos-drill legs.
"""

import pytest

from eegnetreplication_tpu.obs import journal as obs_journal
from eegnetreplication_tpu.obs import schema
from eegnetreplication_tpu.obs.agg import FleetState
from eegnetreplication_tpu.obs.top import _HEADERS, _run_row
from eegnetreplication_tpu.serve.admission import ArrivalWindow
from eegnetreplication_tpu.serve.fleet import membership as ms
from eegnetreplication_tpu.serve.fleet.autoscaler import (
    Autoscaler,
    AutoscalerPolicy,
)

# A port nothing listens on: connection-refused, instantly.
DEAD_URL = "http://127.0.0.1:9/"


def _fake_clock():
    t = {"v": 0.0}
    return t, (lambda: t["v"]), (lambda s: t.__setitem__("v", t["v"] + s))


def _replica(rid, jr, state=ms.LIVE):
    r = ms.Replica(rid, DEAD_URL, journal=jr)
    r.state = state
    return r


class FakeScaler:
    """The autoscaler's action seam, minus processes: spawn registers a
    JOINING member, retire removes it — both against the REAL membership
    state machine."""

    def __init__(self, membership, jr, fail_spawns=0):
        self.membership = membership
        self.jr = jr
        self.fail_spawns = fail_spawns
        self.next_i = len(membership.replicas)
        self.retired = []

    def spawn(self):
        if self.fail_spawns > 0:
            self.fail_spawns -= 1
            raise RuntimeError("spawn boom")
        replica = ms.Replica(f"r{self.next_i}", DEAD_URL, journal=self.jr)
        self.next_i += 1
        self.membership.add_replica(replica)
        return replica

    def retire(self, replica):
        self.membership.remove_replica(replica)
        self.retired.append(replica.replica_id)
        return True


def _fleet(jr, n=1, state=ms.LIVE, poll_s=60.0):
    replicas = [_replica(f"r{i}", jr, state=state) for i in range(n)]
    membership = ms.FleetMembership(replicas, poll_s=poll_s, journal=jr)
    return membership, FakeScaler(membership, jr)


def _scale_events(jr):
    events = schema.read_events(jr.events_path, complete=False)
    assert not any("_schema_error" in e for e in events), events
    return events, [e for e in events if e["event"] == "fleet_scale"]


class TestControlLaw:
    def _autoscaler(self, mem, scaler, stats, jr, clock, sleep, **policy):
        policy.setdefault("min_replicas", 1)
        policy.setdefault("max_replicas", 3)
        policy.setdefault("interval_s", 0.05)
        policy.setdefault("up_cooldown_s", 2.0)
        policy.setdefault("down_cooldown_s", 2.0)
        return Autoscaler(mem, scaler, lambda: dict(stats),
                          policy=AutoscalerPolicy(**policy), journal=jr,
                          clock=clock, sleep=sleep)

    def test_up_on_utilization_with_cooldown_and_ceiling(self, tmp_path):
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            mem, scaler = _fleet(jr, n=1)
            t, clock, sleep = _fake_clock()
            stats = {"arrival_rps": 100.0, "ok_rps": 10.0, "p95_ms": None}
            a = self._autoscaler(mem, scaler, stats, jr, clock, sleep)
            a.tick()  # capacity 10/replica -> utilization 10 -> up
            assert [r.replica_id for r in mem.replicas] == ["r0", "r1"]
            assert a.n_ups == 1
            a.tick()  # same instant: inside the up cooldown, hold
            assert a.n_ups == 1 and len(mem.replicas) == 2
            t["v"] = 2.5
            a.tick()  # cooldown over, still saturated -> up again
            assert [r.replica_id for r in mem.replicas] \
                == ["r0", "r1", "r2"]
            t["v"] = 5.0
            a.tick()  # at max_replicas: hold forever
            assert len(mem.replicas) == 3 and a.n_ups == 2
            mem.close()
        events, scales = _scale_events(jr)
        ups = [e for e in scales if e["action"] == "up"]
        assert len(ups) == 2
        # The decision carries its full input snapshot.
        assert ups[0]["capacity_rps"] == 10.0
        assert ups[0]["utilization"] == 10.0
        assert ups[0]["members"] == {"r0": "live"}
        assert scales[0]["action"] == "resync"

    def test_spawn_failure_journals_holds_and_retries(self, tmp_path):
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            mem, scaler = _fleet(jr, n=1)
            scaler.fail_spawns = 1
            t, clock, sleep = _fake_clock()
            stats = {"arrival_rps": 100.0, "ok_rps": 10.0, "p95_ms": None}
            a = self._autoscaler(mem, scaler, stats, jr, clock, sleep,
                                 max_replicas=2)
            a.tick()  # decision -> spawn raises
            assert a.n_spawn_failures == 1
            assert len(mem.replicas) == 1, "failed spawn left a member"
            a.tick()  # cooldown: the retry is paced, never a hot loop
            assert a.n_ups == 1
            t["v"] = 2.5
            a.tick()  # cooldown over -> clean spawn
            assert [r.replica_id for r in mem.replicas] == ["r0", "r1"]
            mem.close()
        _, scales = _scale_events(jr)
        assert [e["action"] for e in scales] \
            == ["resync", "up", "up_failed", "up"]

    def test_down_drains_and_journal_proves_ordering(self, tmp_path):
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            mem, scaler = _fleet(jr, n=2)
            t, clock, sleep = _fake_clock()
            # capacity 20/replica, arrival 2 -> utilization 0.05.
            stats = {"arrival_rps": 2.0, "ok_rps": 40.0, "p95_ms": None}
            a = self._autoscaler(mem, scaler, stats, jr, clock, sleep)
            a.tick()
            assert a.n_downs == 1 and a.n_forced == 0
            assert [r.replica_id for r in mem.replicas] == ["r0"]
            assert scaler.retired == ["r1"]  # ties retire the high index
            a.tick()  # at min_replicas (and n_live == 1): hold
            assert a.n_downs == 1
            mem.close()
        events, scales = _scale_events(jr)
        assert [e["action"] for e in scales] \
            == ["resync", "down", "drained"]
        assert scales[1]["replica"] == scales[2]["replica"] == "r1"
        assert scales[2]["inflight"] == 0 and scales[2]["queue_depth"] == 0
        # The drain-safety ordering invariant: decision -> quiesce proof
        # -> the member's out/"retired" transition, in the journal.
        i_down = events.index(scales[1])
        i_drained = events.index(scales[2])
        i_retired = next(i for i, e in enumerate(events)
                         if e["event"] == "fleet_member"
                         and e.get("replica") == "r1"
                         and e.get("state") == "out"
                         and e.get("reason") == "retired")
        assert i_down < i_drained < i_retired

    def test_adopted_drain_times_out_into_forced_retirement(self,
                                                            tmp_path):
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            mem, scaler = _fleet(jr, n=2)
            wedged = mem.by_id("r1")
            wedged.pinned = True
            wedged.state = ms.DRAINING
            wedged.begin()  # an in-flight that never completes
            t, clock, sleep = _fake_clock()
            stats = {"arrival_rps": 0.0, "ok_rps": 0.0, "p95_ms": None}
            a = self._autoscaler(mem, scaler, stats, jr, clock, sleep,
                                 min_replicas=1, drain_timeout_s=1.0)
            a.tick()  # resumes the adopted drain; the fake clock walks
            assert a.n_forced == 1  # it past the timeout
            assert scaler.retired == ["r1"]
            assert t["v"] >= 1.0
            assert not any(r.pinned for r in mem.replicas)
            mem.close()
        _, scales = _scale_events(jr)
        resync = scales[0]
        assert resync["action"] == "resync"
        assert resync["adopted_drains"] == ["r1"]
        forced = [e for e in scales if e["action"] == "forced"]
        assert len(forced) == 1
        assert forced[0]["reason"] == "drain_timeout"
        assert forced[0]["inflight"] == 1
        assert not any(e["action"] == "drained" for e in scales)

    def test_stillborn_join_is_reaped(self, tmp_path):
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            mem, scaler = _fleet(jr, n=1)
            t, clock, sleep = _fake_clock()
            stats = {"arrival_rps": 100.0, "ok_rps": 10.0, "p95_ms": None}
            a = self._autoscaler(mem, scaler, stats, jr, clock, sleep,
                                 join_timeout_s=5.0)
            a.tick()  # spawns r1; it stays JOINING (nothing polls)
            assert len(mem.replicas) == 2
            stats.update(arrival_rps=0.0, ok_rps=0.0)
            t["v"] = 10.0
            a.tick()  # past join_timeout_s: reap the stillborn
            assert [r.replica_id for r in mem.replicas] == ["r0"]
            assert scaler.retired == ["r1"]
            mem.close()
        _, scales = _scale_events(jr)
        stillborn = [e for e in scales if e["action"] == "up_failed"]
        assert len(stillborn) == 1
        assert stillborn[0]["reason"] == "stillborn"
        assert stillborn[0]["replica"] == "r1"

    def test_anti_flap_guard_blocks_marginal_down(self, tmp_path):
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            mem, scaler = _fleet(jr, n=2)
            t, clock, sleep = _fake_clock()
            # capacity 10/replica.  utilization 0.44 is below the 0.45
            # band, but post-removal it would be 0.88 > 0.5: removing
            # the replica would immediately re-trigger a scale-up.
            stats = {"arrival_rps": 8.8, "ok_rps": 20.0, "p95_ms": None}
            a = self._autoscaler(mem, scaler, stats, jr, clock, sleep,
                                 up_threshold=0.5, down_threshold=0.45)
            a.tick()
            assert a.n_downs == 0 and len(mem.replicas) == 2
            stats["arrival_rps"] = 4.0  # 0.2 / projected 0.4: clear
            a.tick()
            assert a.n_downs == 1 and len(mem.replicas) == 1
            mem.close()

    def test_idle_fleet_never_shrinks_below_min(self, tmp_path):
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            mem, scaler = _fleet(jr, n=1)
            t, clock, sleep = _fake_clock()
            stats = {"arrival_rps": 0.0, "ok_rps": 0.0, "p95_ms": None}
            a = self._autoscaler(mem, scaler, stats, jr, clock, sleep)
            for _ in range(5):
                a.tick()
                t["v"] += 5.0
            assert a.n_downs == 0 and len(mem.replicas) == 1
            mem.close()

    def test_policy_validation(self):
        with pytest.raises(ValueError):
            AutoscalerPolicy(min_replicas=3, max_replicas=2)
        with pytest.raises(ValueError):
            AutoscalerPolicy(up_threshold=0.3, down_threshold=0.4)
        with pytest.raises(ValueError):
            AutoscalerPolicy(interval_s=0.0)


class TestDynamicMembership:
    def test_add_replica_joins_gated_and_duplicate_raises(self, tmp_path):
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            mem, _ = _fleet(jr, n=1)
            fresh = ms.Replica("r1", DEAD_URL, journal=jr)
            mem.add_replica(fresh)
            # New members enter through the JOINING health gate, never
            # straight into rotation.
            assert fresh.state == ms.JOINING
            assert fresh not in mem.dispatchable()
            assert [r.replica_id for r in mem.replicas] == ["r0", "r1"]
            with pytest.raises(ValueError):
                mem.add_replica(ms.Replica("r1", DEAD_URL, journal=jr))
            mem.close()

    def test_remove_replica_journals_retired_once(self, tmp_path):
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            mem, _ = _fleet(jr, n=2)
            r1 = mem.by_id("r1")
            mem.remove_replica(r1)
            assert [r.replica_id for r in mem.replicas] == ["r0"]
            mem.remove_replica(r1)  # idempotent, no second transition
            mem.close()
        events = schema.read_events(jr.events_path, complete=False)
        retired = [e for e in events if e["event"] == "fleet_member"
                   and e.get("replica") == "r1"
                   and e.get("state") == "out"
                   and e.get("reason") == "retired"]
        assert len(retired) == 1

    def test_pinned_drain_is_exempt_from_health_verdicts(self, tmp_path):
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            mem, _ = _fleet(jr, n=1)
            victim = mem.by_id("r0")
            victim.pinned = True
            victim.state = ms.DRAINING
            # The replica is healthy ON PURPOSE while its in-flight work
            # quiesces; re-LIVE-ing it would hand it new dispatches
            # mid-retirement.  Pinned blocks exactly that verdict.
            victim.client.request = lambda *a, **k: (200, b"{}")
            mem.poll_once()
            assert victim.state == ms.DRAINING
            victim.pinned = False
            mem.poll_once()
            assert victim.state == ms.LIVE
            mem.close()

    def test_pinning_does_not_mask_death(self, tmp_path):
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            mem, _ = _fleet(jr, n=1)
            victim = mem.by_id("r0")
            victim.pinned = True
            victim.state = ms.DRAINING
            # The URL is dead: the process behind the drain crashed.
            # Pinning holds the replica OUT of rotation, not ON life
            # support — the poller still pulls a corpse.
            mem.fail_threshold = 1
            mem.poll_once()
            assert victim.state == ms.OUT
            mem.close()


class TestArrivalWindow:
    def test_rate_over_full_window_and_pruning(self):
        t = {"v": 0.0}
        w = ArrivalWindow(window_s=2.0, clock=lambda: t["v"])
        w.record()
        w.record(3)
        # 4 arrivals over the FULL 2 s window — a just-started burst
        # reads low-but-rising, not as an instant spike.
        assert w.rate() == pytest.approx(2.0)
        t["v"] = 1.9
        assert w.rate() == pytest.approx(2.0)
        t["v"] = 2.5  # the burst ages out of the window
        assert w.rate() == 0.0

    def test_window_validation(self):
        with pytest.raises(ValueError):
            ArrivalWindow(window_s=0.0)


class TestScaleObservability:
    _SCALES = [
        {"event": "fleet_scale", "t": 95.0, "run_id": "ra", "action": "up",
         "target": 2, "n_live": 1, "reason": "utilization 1.2 > 0.85"},
        {"event": "fleet_scale", "t": 96.0, "run_id": "ra",
         "action": "down", "target": 1, "n_live": 2,
         "reason": "utilization 0.1 < 0.40", "replica": "r1"},
        {"event": "fleet_scale", "t": 96.5, "run_id": "ra",
         "action": "forced", "target": 1, "n_live": 1,
         "reason": "drain_timeout", "replica": "r1"},
    ]

    def test_schema_requires_the_decision_keys(self):
        ok = schema.validate_event(dict(self._SCALES[0]))
        assert ok["action"] == "up"
        missing = {k: v for k, v in self._SCALES[0].items()
                   if k != "reason"}
        with pytest.raises(schema.SchemaError):
            schema.validate_event(missing)

    def test_event_summary_counts_scale_actions(self):
        out = schema.event_summary(list(self._SCALES))
        assert out["scale_ups"] == 1
        assert out["scale_downs"] == 1
        assert out["forced_retires"] == 1

    def test_fleet_state_folds_scale_and_top_renders_it(self):
        state = FleetState(window_s=60.0, clock=lambda: 100.0)
        state.fold("runA", [
            {"event": "run_start", "t": 90.0, "run_id": "ra",
             "platform": "cpu"},
            *self._SCALES,
        ])
        run = state.snapshot()["runs"][0]
        assert run["scale"] == {"target": 1, "actual": 1, "ups": 1,
                                "downs": 1, "forced": 1}
        row = _run_row(run)
        assert row[_HEADERS.index("scale")] == "1/1"
        # A run with no scale events renders a placeholder, not a crash.
        assert _run_row({})[_HEADERS.index("scale")] == "-"
