"""Minimal MNE test double (VERDICT r2 item 9).

MNE is not installed in this image, so the ``.fif`` ingest branches
(``data/epoching.py::build_dataset_from_fif_dir``,
``data/moabb.py::load_moabb_run``) would be import-gated dead code in CI.
This double implements exactly the API slice those branches touch —
``mne.io.read_raw_fif``, ``mne.events_from_annotations``, ``mne.Epochs``
— backed by ``.npz`` payloads wearing ``.fif`` names
(:func:`write_fake_fif`), with MNE's semantics where they matter:

- ``Epochs`` windows are inclusive of ``tmax`` (``tmin=0.5, tmax=2.5`` at
  128 Hz -> samples 64..320 -> 257);
- epochs whose window falls off the recording are DROPPED and
  ``.selection`` records the surviving indices within the event-id-matched
  list (the property ``build_dataset_from_fif_dir`` relies on for
  TrueLabels alignment);
- ``Raw.pick("eeg")`` filters by channel type (the moabb loader's EOG
  drop).

Install via :func:`install` (registers ``mne`` in ``sys.modules``); tests
skip the double automatically when the real MNE is importable.
"""

from __future__ import annotations

import sys
import types

import numpy as np


class _Annotations:
    def __init__(self, onset, description):
        self.onset = np.asarray(onset, float)
        self.description = np.asarray([str(d) for d in description],
                                      dtype=object)


class _RawFif:
    def __init__(self, data, sfreq, ch_names, ch_types, onsets, descs):
        self._data = np.asarray(data, float)
        self._ch_types = [str(t) for t in ch_types]
        self.ch_names = [str(c) for c in ch_names]
        self.info = {"sfreq": float(sfreq)}
        self.annotations = _Annotations(onsets, descs)

    def pick(self, picks):
        keep = [i for i, t in enumerate(self._ch_types) if t == picks]
        self._data = self._data[keep]
        self.ch_names = [self.ch_names[i] for i in keep]
        self._ch_types = [self._ch_types[i] for i in keep]
        return self

    def get_data(self):
        return self._data


def write_fake_fif(path, data, sfreq, ch_names, onsets_s, descriptions,
                   ch_types=None) -> None:
    """Write an ``.npz`` payload under a ``.fif`` name for read_raw_fif."""
    ch_types = ch_types or ["eeg"] * len(ch_names)
    with open(path, "wb") as f:  # np.savez(path) would append ".npz"
        np.savez(f, data=np.asarray(data, float), sfreq=float(sfreq),
                 ch_names=np.asarray(ch_names, object),
                 ch_types=np.asarray(ch_types, object),
                 onsets=np.asarray(onsets_s, float),
                 descs=np.asarray([str(d) for d in descriptions], object))


def read_raw_fif(path, preload=True, verbose=None) -> _RawFif:
    z = np.load(path, allow_pickle=True)
    return _RawFif(z["data"], float(z["sfreq"]), list(z["ch_names"]),
                   list(z["ch_types"]), z["onsets"], list(z["descs"]))


def events_from_annotations(raw, verbose=None):
    descs = sorted({str(d) for d in raw.annotations.description})
    event_id = {d: i + 1 for i, d in enumerate(descs)}
    sf = raw.info["sfreq"]
    events = np.asarray(
        [[int(round(o * sf)), 0, event_id[str(d)]]
         for o, d in zip(raw.annotations.onset,
                         raw.annotations.description)],
        int).reshape(-1, 3)
    return events, event_id


class Epochs:
    def __init__(self, raw, events, event_id=None, tmin=0.0, tmax=1.0,
                 baseline=None, preload=True, verbose=None):
        sf = raw.info["sfreq"]
        lo, hi = int(round(tmin * sf)), int(round(tmax * sf))
        codes = set((event_id or {}).values())
        data = raw.get_data()
        matched = [e for e in np.asarray(events).reshape(-1, 3)
                   if int(e[2]) in codes]
        sel, wins, evs = [], [], []
        for j, e in enumerate(matched):
            a = int(e[0]) + lo
            b = int(e[0]) + hi + 1  # inclusive tmax, like MNE
            if a < 0 or b > data.shape[1]:
                continue  # off-recording window: dropped, like MNE
            sel.append(j)
            wins.append(data[:, a:b])
            evs.append(e)
        self.selection = np.asarray(sel, int)
        self.events = np.asarray(evs, int).reshape(-1, 3)
        self._wins = (np.asarray(wins, float) if wins
                      else np.zeros((0, data.shape[0], hi - lo + 1)))

    def get_data(self):
        return self._wins


def install() -> types.ModuleType:
    """Register the double as ``mne`` / ``mne.io`` in ``sys.modules``."""
    mne = types.ModuleType("mne")
    io_mod = types.ModuleType("mne.io")
    io_mod.read_raw_fif = read_raw_fif
    mne.io = io_mod
    mne.events_from_annotations = events_from_annotations
    mne.Epochs = Epochs
    sys.modules["mne"] = mne
    sys.modules["mne.io"] = io_mod
    return mne


def uninstall() -> None:
    sys.modules.pop("mne", None)
    sys.modules.pop("mne.io", None)
