"""Unit tests of the inference layer (predict.py) below the CLI boundary."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from eegnetreplication_tpu.models import EEGNet  # noqa: E402
from eegnetreplication_tpu.predict import (  # noqa: E402
    load_model_from_checkpoint,
    predict_trials,
)


@pytest.fixture(scope="module")
def small_model():
    model = EEGNet(n_channels=6, n_times=64)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 6, 64)),
                           train=False)
    return model, variables["params"], variables["batch_stats"]


class TestPredictTrials:
    def test_matches_direct_forward(self, small_model):
        model, params, bs = small_model
        x = np.random.RandomState(0).randn(40, 6, 64).astype(np.float32)
        pred = predict_trials(model, params, bs, x, batch_size=16)
        logits = model.apply({"params": params, "batch_stats": bs},
                             jnp.asarray(x), train=False)
        np.testing.assert_array_equal(pred, np.argmax(np.asarray(logits), 1))

    def test_ragged_final_batch_padding(self, small_model):
        """n not divisible by batch_size: padded tail predictions dropped."""
        model, params, bs = small_model
        x = np.random.RandomState(1).randn(37, 6, 64).astype(np.float32)
        pred = predict_trials(model, params, bs, x, batch_size=16)
        assert pred.shape == (37,)
        full = predict_trials(model, params, bs, x, batch_size=64)
        np.testing.assert_array_equal(pred, full)

    def test_empty_input(self, small_model):
        model, params, bs = small_model
        pred = predict_trials(model, params, bs,
                              np.zeros((0, 6, 64), np.float32))
        assert pred.shape == (0,)


class TestCheckpointGeometry:
    def test_npz_roundtrip_any_registry_model(self, tmp_path):
        from eegnetreplication_tpu.models import get_model
        from eegnetreplication_tpu.training.checkpoint import save_checkpoint

        model = get_model("shallow_convnet", n_channels=6, n_times=64)
        variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 6, 64)),
                               train=False)
        p = tmp_path / "m.npz"
        save_checkpoint(p, variables["params"], variables["batch_stats"],
                        metadata={"model": "shallow_convnet",
                                  "n_channels": 6, "n_times": 64})
        loaded_model, params, bs = load_model_from_checkpoint(p)
        x = np.random.RandomState(0).randn(4, 6, 64).astype(np.float32)
        a = model.apply(variables, jnp.asarray(x), train=False)
        b = loaded_model.apply(
            {"params": jax.tree_util.tree_map(jnp.asarray, params),
             "batch_stats": jax.tree_util.tree_map(jnp.asarray, bs)},
            jnp.asarray(x), train=False)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    def test_pth_auto_infers_wide_geometry(self, tmp_path):
        torch = pytest.importorskip("torch")  # noqa: F841
        from eegnetreplication_tpu.models import eegnet_wide
        from eegnetreplication_tpu.training.checkpoint import (
            load_pth_auto,
            save_pth,
        )

        model = eegnet_wide(n_channels=10, n_times=257)
        variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 10, 257)),
                               train=False)
        p = tmp_path / "wide.pth"
        save_pth(p, variables["params"], variables["batch_stats"],
                 f2=model.F2, t_prime=257 // 32)
        _, _, meta = load_pth_auto(p)
        assert meta == {"model": "eegnet", "n_channels": 10, "n_times": 257,
                        "F1": 16, "D": 4}

    def test_pth_auto_rejects_bad_geometry(self, tmp_path):
        torch = pytest.importorskip("torch")
        from eegnetreplication_tpu.training.checkpoint import load_pth_auto

        sd = {
            "temporal.0.weight": torch.zeros(8, 1, 1, 32),
            "spatial.weight": torch.zeros(20, 1, 22, 1),  # F2=20, F1=8
            "classifier.weight": torch.zeros(4, 160),
            "classifier.bias": torch.zeros(4),
        }
        p = tmp_path / "bad.pth"
        torch.save(sd, p)
        with pytest.raises(ValueError, match="multiple of F1"):
            load_pth_auto(p)


class TestOrbaxCheckpointLoading:
    def test_orbax_directory_roundtrip(self, tmp_path, small_model):
        """predict's loader accepts an Orbax checkpoint directory."""
        pytest.importorskip("orbax.checkpoint")
        from eegnetreplication_tpu.training.orbax_io import (
            save_orbax_checkpoint,
        )

        model, params, bs = small_model
        p = save_orbax_checkpoint(
            tmp_path / "orbax_ck", params, bs,
            {"model": "eegnet", "n_channels": 6, "n_times": 64,
             "F1": 8, "D": 2})
        loaded_model, lp, lbs = load_model_from_checkpoint(p)
        assert (loaded_model.n_channels, loaded_model.n_times) == (6, 64)
        x = np.random.RandomState(2).randn(8, 6, 64).astype(np.float32)
        np.testing.assert_array_equal(
            predict_trials(model, params, bs, x),
            predict_trials(loaded_model, lp, lbs, x))


class TestInferenceThroughputLine:
    def test_logged_with_gflops(self, small_model, caplog):
        import logging

        from eegnetreplication_tpu.predict import _log_inference_throughput

        model, _, _ = small_model
        with caplog.at_level(logging.INFO):
            _log_inference_throughput(model, n_trials=100, wall=0.5,
                                      batch_size=16)
        lines = [r.getMessage() for r in caplog.records
                 if r.getMessage().startswith("Inference: ")]
        assert lines and "trials/s" in lines[0]
        assert "GFLOP/s" in lines[0]  # cost model available on CPU
