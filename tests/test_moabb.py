"""Tests of the repaired moabb preprocessing path (data/moabb.py).

MNE/moabb are absent in CI (like the reference's environment-gated path);
the run-merge logic and tree-driving behavior are pure numpy and fully
tested.  The MNE-touching loader is checked for its actionable gating error.
"""

import shutil
import tempfile
import unittest
from pathlib import Path

import numpy as np

from eegnetreplication_tpu.config import Paths
from eegnetreplication_tpu.data.moabb import (
    MOABB_DESC_TO_CODE,
    load_moabb_run,
    merge_processed,
    preprocess_moabb_data,
)
from eegnetreplication_tpu.data.preprocess import ProcessedRecording


def _rec(n_samples, events, seed=0, sfreq=128.0):
    rng = np.random.RandomState(seed)
    pos = np.asarray([p for p, _ in events], np.int64)
    typ = np.asarray([t for _, t in events], np.int64)
    return ProcessedRecording(
        data=rng.randn(4, n_samples).astype(np.float32), sfreq=sfreq,
        labels=["C1", "C2", "C3", "C4"], event_pos=pos, event_typ=typ)


class TestMergeProcessed(unittest.TestCase):
    def test_positions_offset_by_run_lengths(self):
        a = _rec(100, [(10, 769), (50, 770)], seed=1)
        b = _rec(80, [(5, 771)], seed=2)
        c = _rec(60, [(0, 772)], seed=3)
        m = merge_processed([a, b, c])
        self.assertEqual(m.data.shape, (4, 240))
        np.testing.assert_array_equal(m.event_pos, [10, 50, 105, 180])
        np.testing.assert_array_equal(m.event_typ, [769, 770, 771, 772])
        np.testing.assert_array_equal(m.data[:, 100:180], b.data)

    def test_single_run_is_identity(self):
        a = _rec(100, [(10, 769)])
        m = merge_processed([a])
        np.testing.assert_array_equal(m.data, a.data)
        np.testing.assert_array_equal(m.event_pos, a.event_pos)

    def test_mismatched_sfreq_rejected(self):
        with self.assertRaisesRegex(ValueError, "sampling rate"):
            merge_processed([_rec(10, [], sfreq=128.0),
                             _rec(10, [], sfreq=250.0)])

    def test_empty_rejected(self):
        with self.assertRaisesRegex(ValueError, "at least one"):
            merge_processed([])


class TestMoabbTree(unittest.TestCase):
    def test_desc_map_covers_named_and_numeric(self):
        self.assertEqual(MOABB_DESC_TO_CODE["left_hand"], 769)
        self.assertEqual(MOABB_DESC_TO_CODE["tongue"], 772)
        self.assertEqual(MOABB_DESC_TO_CODE["770"], 770)

    def test_loader_gating_error_is_actionable(self):
        try:
            import mne  # noqa: F401
            self.skipTest("MNE installed; gating not exercised")
        except ImportError:
            pass
        with self.assertRaisesRegex(ImportError, "requires MNE"):
            load_moabb_run("/nonexistent/run.fif")

    def test_empty_tree_warns_but_returns(self):
        tmp = Path(tempfile.mkdtemp(prefix="eegtpu_moabb_"))
        try:
            written = preprocess_moabb_data(Paths.from_root(tmp))
            self.assertEqual(written, [])
        finally:
            shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    unittest.main()
