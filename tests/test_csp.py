"""Tests of the JAX-native CSP+LDA classical baseline (notebook 01/03 twin)."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from eegnetreplication_tpu.models.csp import (  # noqa: E402
    csp_fit,
    csp_lda_accuracy,
    csp_lda_fit_predict,
    csp_transform,
    lda_fit,
    lda_scores,
)


def _oscillatory_data(n_per_class=40, n_channels=8, n_times=128, seed=0,
                      snr=1.5):
    """4 classes, each with band power concentrated on a different channel
    pair — the textbook CSP-separable construction."""
    rng = np.random.RandomState(seed)
    X, y = [], []
    t = np.arange(n_times)
    for k in range(4):
        for _ in range(n_per_class):
            x = rng.randn(n_channels, n_times) * 0.5
            f = 6 + 3 * k
            phase = rng.rand() * 2 * np.pi
            osc = np.sin(2 * np.pi * f * t / 128.0 + phase)
            x[2 * k % n_channels] += snr * osc * rng.uniform(0.8, 1.2)
            x[(2 * k + 1) % n_channels] += snr * osc * rng.uniform(0.4, 0.6)
            X.append(x)
            y.append(k)
    X = np.asarray(X, np.float32)
    y = np.asarray(y, np.int32)
    perm = rng.permutation(len(y))
    return X[perm], y[perm]


class TestCSP:
    def test_filter_shape(self):
        X, y = _oscillatory_data(n_per_class=10)
        filters = csp_fit(jnp.asarray(X), jnp.asarray(y), n_components=2)
        assert filters.shape == (8, 8)  # 4 classes x 2 components, C=8

    def test_features_shape_and_finite(self):
        X, y = _oscillatory_data(n_per_class=10)
        filters = csp_fit(jnp.asarray(X), jnp.asarray(y), n_components=3)
        feats = csp_transform(jnp.asarray(X), filters)
        assert feats.shape == (len(y), 12)
        assert bool(jnp.all(jnp.isfinite(feats)))

    def test_csp_filters_separate_classes(self):
        """Class-k filters should extract more variance from class-k trials."""
        X, y = _oscillatory_data()
        filters = csp_fit(jnp.asarray(X), jnp.asarray(y), n_components=1)
        proj = np.asarray(csp_transform(jnp.asarray(X), filters))
        # Feature k (the class-k filter's log-power) should be maximal for
        # trials of class k more often than chance.
        hit = np.mean(np.argmax(proj, axis=1) == y)
        assert hit > 0.5


class TestLDA:
    def test_separable_gaussians(self):
        rng = np.random.RandomState(1)
        means = np.array([[0, 0], [4, 0], [0, 4], [4, 4]], np.float32)
        F = np.concatenate([rng.randn(50, 2).astype(np.float32) + m
                            for m in means])
        y = np.repeat(np.arange(4), 50).astype(np.int32)
        model = lda_fit(jnp.asarray(F), jnp.asarray(y))
        pred = np.asarray(jnp.argmax(lda_scores(model, jnp.asarray(F)), axis=1))
        assert np.mean(pred == y) > 0.95


class TestPipeline:
    def test_beats_chance_decisively(self):
        X, y = _oscillatory_data(n_per_class=60)
        n = len(y)
        acc = csp_lda_accuracy(X[: n // 2], y[: n // 2],
                               X[n // 2:], y[n // 2:])
        assert acc > 60.0  # chance is 25%

    def test_vmappable_over_folds(self):
        """The whole fit+predict runs under vmap — the TPU-native win the
        sklearn/mne stack cannot offer."""
        X, y = _oscillatory_data(n_per_class=30)
        n = len(y)
        half = n // 2
        stacked_train_x = jnp.stack([jnp.asarray(X[:half])] * 3)
        stacked_train_y = jnp.stack([jnp.asarray(y[:half])] * 3)
        stacked_test_x = jnp.stack([jnp.asarray(X[half:])] * 3)
        preds = jax.vmap(
            lambda a, b, c: csp_lda_fit_predict(a, b, c)
        )(stacked_train_x, stacked_train_y, stacked_test_x)
        assert preds.shape == (3, n - half)
        assert bool(jnp.all(preds[0] == preds[1]))

    def test_prediction_values_in_range(self):
        X, y = _oscillatory_data(n_per_class=15)
        pred = csp_lda_fit_predict(jnp.asarray(X), jnp.asarray(y),
                                   jnp.asarray(X))
        assert set(np.unique(np.asarray(pred))) <= {0, 1, 2, 3}
