"""TorchBatchNorm: torch-exact semantics + padding-mask tests.

The mechanism arm of the round-5 accuracy-equivalence ablation
(VERDICT r4 item 2): masked batch statistics and the unbiased running-
variance update must reproduce torch ``BatchNorm2d`` exactly, so that
``EEGNet(bn_mode="torch")`` differs from the reference by seed noise only.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from eegnetreplication_tpu.models.eegnet import EEGNet  # noqa: E402
from eegnetreplication_tpu.models.norm import TorchBatchNorm  # noqa: E402


def _init_and_apply(x, weights=None, momentum=0.9, train=True):
    bn = TorchBatchNorm(momentum=momentum)
    variables = bn.init(jax.random.PRNGKey(0), jnp.asarray(x),
                        use_running_average=False)
    out, updates = bn.apply(
        variables, jnp.asarray(x), use_running_average=not train,
        sample_weights=None if weights is None else jnp.asarray(weights),
        mutable=["batch_stats"])
    return np.asarray(out), {k: np.asarray(v) for k, v in
                             updates["batch_stats"].items()}, variables


class TestTorchSemantics:
    def test_matches_torch_batchnorm2d_train_step(self):
        """Full batch (no mask): normalized output and both running stats
        equal torch BatchNorm2d's after one training step."""
        torch = pytest.importorskip("torch")
        rng = np.random.RandomState(0)
        x = rng.randn(8, 3, 5, 4).astype(np.float32)  # (B, H, W, F)

        out, stats, _ = _init_and_apply(x)

        tbn = torch.nn.BatchNorm2d(4, momentum=0.1)  # = flax momentum 0.9
        with torch.no_grad():
            tout = tbn(torch.from_numpy(
                x.transpose(0, 3, 1, 2)))  # NCHW
        np.testing.assert_allclose(
            out, tout.numpy().transpose(0, 2, 3, 1), atol=2e-5)
        np.testing.assert_allclose(stats["mean"],
                                   tbn.running_mean.numpy(), atol=1e-6)
        # The discriminating check: torch's running update uses the
        # UNBIASED batch variance (flax nn.BatchNorm uses the biased one).
        np.testing.assert_allclose(stats["var"],
                                   tbn.running_var.numpy(), atol=1e-6)

    def test_masked_equals_real_only_batch(self):
        """Wraparound padding (weight 0) must not influence statistics:
        stats and real-sample outputs equal those of the unpadded batch."""
        rng = np.random.RandomState(1)
        real = rng.randn(5, 2, 3, 4).astype(np.float32)
        # Framework-style padded batch: 3 wraparound duplicates, weight 0.
        padded = np.concatenate([real, real[:3]])
        w = np.array([1, 1, 1, 1, 1, 0, 0, 0], np.float32)

        out_p, stats_p, _ = _init_and_apply(padded, weights=w)
        out_r, stats_r, _ = _init_and_apply(real)

        np.testing.assert_allclose(stats_p["mean"], stats_r["mean"],
                                   atol=1e-6)
        np.testing.assert_allclose(stats_p["var"], stats_r["var"], atol=1e-6)
        np.testing.assert_allclose(out_p[:5], out_r, atol=1e-5)

    def test_unmasked_padding_skews_flax_bn(self):
        """Sanity of the mechanism itself: nn.BatchNorm on the padded batch
        does NOT match the real-only batch — the divergence this module
        removes actually exists."""
        import flax.linen as nn

        rng = np.random.RandomState(2)
        real = rng.randn(5, 2, 3, 4).astype(np.float32) + 1.5
        padded = np.concatenate([real, real[:3]])

        bn = nn.BatchNorm(use_running_average=False, momentum=0.9)
        v = bn.init(jax.random.PRNGKey(0), jnp.asarray(real))
        _, up_r = bn.apply(v, jnp.asarray(real), mutable=["batch_stats"])
        _, up_p = bn.apply(v, jnp.asarray(padded), mutable=["batch_stats"])
        assert not np.allclose(np.asarray(up_r["batch_stats"]["mean"]),
                               np.asarray(up_p["batch_stats"]["mean"]),
                               atol=1e-6)

    def test_eval_mode_matches_nn_batchnorm(self):
        """Eval (running stats) is numerically identical to nn.BatchNorm
        given the same parameters and statistics."""
        import flax.linen as nn

        rng = np.random.RandomState(3)
        x = rng.randn(6, 2, 3, 4).astype(np.float32)
        stats = {"mean": jnp.asarray(rng.randn(4).astype(np.float32)),
                 "var": jnp.asarray(
                     rng.uniform(0.5, 2.0, 4).astype(np.float32))}
        params = {"scale": jnp.asarray(
            rng.uniform(0.5, 1.5, 4).astype(np.float32)),
            "bias": jnp.asarray(rng.randn(4).astype(np.float32))}
        variables = {"params": params, "batch_stats": stats}

        ours = TorchBatchNorm().apply(variables, jnp.asarray(x),
                                      use_running_average=True)
        flaxs = nn.BatchNorm(use_running_average=True).apply(
            variables, jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(ours), np.asarray(flaxs),
                                   atol=1e-6)

    def test_all_padding_batch_keeps_stats(self):
        rng = np.random.RandomState(4)
        x = rng.randn(4, 2, 3, 4).astype(np.float32)
        w = np.zeros(4, np.float32)
        _, stats, variables = _init_and_apply(x, weights=w)
        np.testing.assert_array_equal(
            stats["mean"], np.asarray(variables["batch_stats"]["mean"]))
        np.testing.assert_array_equal(
            stats["var"], np.asarray(variables["batch_stats"]["var"]))


class TestEEGNetIntegration:
    def test_bn_mode_torch_trains(self):
        """EEGNet(bn_mode='torch') takes optimizer steps with finite loss
        and updates batch stats; checkpoints share the flax-BN layout."""
        import optax

        from eegnetreplication_tpu.training.steps import (
            TrainState,
            train_step,
        )

        model = EEGNet(n_channels=4, n_times=64, F1=2, D=2,
                       bn_mode="torch")
        x = np.random.RandomState(0).randn(8, 4, 64).astype(np.float32)
        y = np.zeros(8, np.int32)
        w = np.array([1, 1, 1, 1, 1, 1, 0, 0], np.float32)
        variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x))
        flax_variables = EEGNet(n_channels=4, n_times=64, F1=2, D=2).init(
            jax.random.PRNGKey(0), jnp.asarray(x))
        assert (jax.tree_util.tree_structure(variables)
                == jax.tree_util.tree_structure(flax_variables))

        tx = optax.adam(1e-3)
        state = TrainState(params=variables["params"],
                           batch_stats=variables["batch_stats"],
                           opt_state=tx.init(variables["params"]))
        new_state, loss = train_step(model, tx, state, jnp.asarray(x),
                                     jnp.asarray(y), jnp.asarray(w),
                                     jax.random.PRNGKey(1))
        assert np.isfinite(float(loss))
        before = jax.tree_util.tree_leaves(state.batch_stats)
        after = jax.tree_util.tree_leaves(new_state.batch_stats)
        assert any(not np.allclose(np.asarray(b), np.asarray(a))
                   for b, a in zip(before, after))

    def test_invalid_bn_mode_rejected(self):
        with pytest.raises(ValueError, match="bn_mode"):
            EEGNet(bn_mode="caffe")
