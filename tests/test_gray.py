"""Gray-failure resilience (ISSUE 10).

Covers the defense layer against replicas that are slow-yet-alive and
overload that used to be a static cliff: the extended injection registry
(bounded ``slow=`` degradation, ``every=``/``if_tag=`` predicates,
response truncation), the latency-outlier ejection policy (median/k
math, cooldown -> half-open probe -> readmit, max-ejection-fraction
guard, drain-not-drop), hedged dispatch with its hard budget, and the
AIMD admission controller with two-class shedding.

Policy/state-machine tests run against scriptable fakes (no JAX, no
subprocesses); the end-to-end truth — real engines under real degraded
load — is ``serve_bench.py --gray --selftest`` (the last test here).
"""

import json
import math
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from eegnetreplication_tpu.obs import journal as obs_journal
from eegnetreplication_tpu.obs import schema
from eegnetreplication_tpu.resil import inject
from eegnetreplication_tpu.serve.admission import AdmissionController
from eegnetreplication_tpu.serve.batcher import MicroBatcher, Rejected, Shed
from eegnetreplication_tpu.serve.fleet import membership as ms
from eegnetreplication_tpu.serve.fleet.outlier import OutlierEjector
from eegnetreplication_tpu.serve.fleet.router import FleetRouter, HedgePolicy
from test_fleet import FakeReplica

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture
def journal(tmp_path):
    with obs_journal.run(tmp_path / "obs", config={}) as jr:
        yield jr


def _events(jr, kind):
    return [e for e in schema.read_events(jr.events_path, complete=False)
            if e["event"] == kind]


# ---------------------------------------------------------------------------
# Injection-registry extensions (the deterministic gray reproduction).


class TestInjectGray:
    def test_slow_action_is_bounded_latency_not_an_exception(self):
        with inject.scoped(inject.FaultSpec(site="serve.degrade", times=0,
                                            slow=0.05)):
            t0 = time.perf_counter()
            inject.fire("serve.degrade", tag=None)  # returns normally
            assert time.perf_counter() - t0 >= 0.045

    def test_if_tag_confines_the_fault_to_one_tagged_caller(self):
        with inject.scoped(inject.FaultSpec(site="serve.degrade", times=0,
                                            slow=0.05, if_tag="g1")):
            t0 = time.perf_counter()
            inject.fire("serve.degrade", tag="g0")
            inject.fire("serve.degrade", tag=None)
            assert time.perf_counter() - t0 < 0.04  # neither fired
            t0 = time.perf_counter()
            inject.fire("serve.degrade", tag="g1")
            assert time.perf_counter() - t0 >= 0.045

    def test_every_n_fires_periodically(self):
        fired = []
        with inject.scoped(inject.FaultSpec(site="serve.degrade", times=0,
                                            every=3, action="raise",
                                            exc="ValueError")):
            for i in range(1, 10):
                try:
                    inject.fire("serve.degrade", tag=None)
                except ValueError:
                    fired.append(i)
        assert fired == [1, 4, 7]

    def test_truncate_action_raises_the_control_signal(self):
        with inject.scoped(inject.FaultSpec(site="replica.network",
                                            times=1)):
            with pytest.raises(inject.ResponseTruncated):
                inject.fire("replica.network")
            inject.fire("replica.network")  # times=1: spent

    def test_refuse_action_raises_connection_refused(self):
        """cell.partition (ISSUE 12): the client seam sees exactly what a
        dead/partitioned cell produces — a ConnectionRefusedError (an
        OSError, so the dispatch path classifies it as a dead
        connection), confined by if_tag= to one cell id."""
        with inject.scoped(inject.FaultSpec(site="cell.partition", times=0,
                                            refuse=1, if_tag="c1")):
            inject.fire("cell.partition", tag="c0")  # sibling untouched
            with pytest.raises(ConnectionRefusedError):
                inject.fire("cell.partition", tag="c1")
        # refuse is the site's DEFAULT action: a bare spec partitions too.
        with inject.scoped(inject.FaultSpec(site="cell.partition",
                                            times=1)):
            with pytest.raises(ConnectionRefusedError):
                inject.fire("cell.partition")
            inject.fire("cell.partition")  # times=1: spent

    @pytest.mark.parametrize("spec", [
        "cell.partition:refuse=0", "cell.partition:refuse=2",
        "cell.partition:refuse=-1", "cell.partition:refuse=yes",
        "cell.partition:refuse=1:action=raise",
    ])
    def test_malformed_refuse_fails_at_plan_parse_time(self, spec):
        """refuse= gets the same parse-time strictness as slow=/sleep=:
        a typo'd plan fails before the drill starts."""
        with pytest.raises(ValueError):
            inject.parse_plan(spec)

    def test_refuse_parses_from_plan_text_and_file(self, tmp_path):
        specs = inject.parse_plan("cell.partition:refuse=1:if_tag=c0")
        assert specs[0].action == "refuse" and specs[0].if_tag == "c0"
        plan = tmp_path / "plan.json"
        plan.write_text(json.dumps(
            [{"site": "cell.partition", "refuse": 1, "times": 0}]))
        specs = inject.parse_plan(f"@{plan}")
        assert specs[0].action == "refuse" and specs[0].times == 0

    @pytest.mark.parametrize("spec", [
        "serve.degrade:slow=-1", "serve.degrade:slow=inf",
        "serve.degrade:slow=nan", "serve.degrade:slow=oops",
        "train.hang:sleep=-0.5", "train.hang:sleep=nan",
        "serve.degrade:every=0",
    ])
    def test_malformed_durations_fail_at_plan_parse_time(self, spec):
        with pytest.raises(ValueError):
            inject.parse_plan(spec)

    def test_plan_file_validates_slow_too(self, tmp_path):
        plan = tmp_path / "plan.json"
        plan.write_text(json.dumps(
            [{"site": "serve.degrade", "slow": float("inf")}]))
        # json.dumps writes Infinity (non-strict); the parse must reject
        # the value, not smuggle it through to fire time.
        with pytest.raises(ValueError):
            inject.parse_plan(f"@{plan}")
        plan.write_text(json.dumps(
            [{"site": "serve.degrade", "slow": 0.25, "if_tag": "g1",
              "times": 0}]))
        specs = inject.parse_plan(f"@{plan}")
        assert specs[0].slow == 0.25 and specs[0].if_tag == "g1"


# ---------------------------------------------------------------------------
# Latency-outlier ejection policy (no HTTP: latencies fed directly).


def _member_fleet(n, journal, **kw):
    """Replicas with unused URLs (policy tests never dispatch)."""
    replicas = [ms.Replica(f"r{i}", f"http://127.0.0.1:{9000 + i}",
                           journal=journal) for i in range(n)]
    membership = ms.FleetMembership(replicas, journal=journal)
    for r in replicas:
        r.state = ms.LIVE
    ejector = OutlierEjector(membership, journal=journal, **kw)
    return replicas, membership, ejector


def _feed(ejector, replica, latencies):
    for lat in latencies:
        ejector.observe(replica, lat)


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


class TestOutlierPolicy:
    def test_slow_replica_ejected_on_k_times_fleet_median(self, journal):
        replicas, _, ejector = _member_fleet(
            3, journal, k=3.0, min_samples=4, floor_ms=1.0,
            check_interval_s=0.0)
        _feed(ejector, replicas[0], [10.0] * 8)
        _feed(ejector, replicas[2], [12.0] * 8)
        _feed(ejector, replicas[1], [100.0] * 8)  # p95 100 > 3x median 11
        assert replicas[1].state == ms.DEGRADED
        assert ejector.n_ejected == 1
        ev = _events(journal, "replica_ejected")
        assert len(ev) == 1
        assert ev[0]["replica"] == "r1"
        assert ev[0]["p95_ms"] == pytest.approx(100.0)
        assert ev[0]["fleet_p50_ms"] == pytest.approx(12.0)
        # Degraded replicas leave dispatch rotation entirely.
        assert [r.replica_id for r in
                ejector.membership.dispatchable()] == ["r0", "r2"]

    def test_under_k_stays_live_and_median_resists_the_outlier(self,
                                                               journal):
        # The threshold is k x the median of per-replica MEDIANS: the
        # slow replica's own latencies cannot drag the fleet baseline up.
        replicas, _, ejector = _member_fleet(
            3, journal, k=3.0, min_samples=4, floor_ms=1.0,
            check_interval_s=0.0)
        _feed(ejector, replicas[0], [10.0] * 8)
        _feed(ejector, replicas[2], [10.0] * 8)
        _feed(ejector, replicas[1], [25.0] * 8)  # 2.5x: not an outlier
        assert all(r.state == ms.LIVE for r in replicas)
        assert ejector.n_ejected == 0

    def test_floor_ms_suppresses_microsecond_noise(self, journal):
        # p95 3x the median but under the absolute floor: all-fast fleets
        # with scheduler jitter must not eject anybody.
        replicas, _, ejector = _member_fleet(
            2, journal, k=3.0, min_samples=4, floor_ms=5.0,
            check_interval_s=0.0)
        _feed(ejector, replicas[0], [0.5] * 8)
        _feed(ejector, replicas[1], [4.0] * 8)
        assert all(r.state == ms.LIVE for r in replicas)

    def test_max_eject_fraction_guard_never_evicts_past_the_cap(self,
                                                                journal):
        replicas, _, ejector = _member_fleet(
            4, journal, k=3.0, min_samples=4, floor_ms=1.0,
            max_eject_fraction=0.25, check_interval_s=0.0)
        _feed(ejector, replicas[0], [10.0] * 8)
        _feed(ejector, replicas[3], [10.0] * 8)
        _feed(ejector, replicas[1], [200.0] * 8)
        assert replicas[1].state == ms.DEGRADED  # 1/4 <= 0.25: allowed
        _feed(ejector, replicas[2], [300.0] * 8)
        assert replicas[2].state == ms.LIVE      # 2/4 > 0.25: refused
        assert ejector.n_ejected == 1

    def test_cooldown_probe_readmit_cycle(self, journal):
        clock = FakeClock()
        replicas, _, ejector = _member_fleet(
            3, journal, k=3.0, min_samples=4, floor_ms=1.0,
            cooldown_s=5.0, check_interval_s=0.0, clock=clock)
        _feed(ejector, replicas[0], [10.0] * 8)
        _feed(ejector, replicas[2], [10.0] * 8)
        _feed(ejector, replicas[1], [100.0] * 8)
        assert replicas[1].state == ms.DEGRADED
        # Inside the cooldown: no probe slots.
        assert ejector.claim_probe(set()) is None
        clock.t += 5.1
        probe = ejector.claim_probe(set())
        assert probe is replicas[1]
        # Only one probe slot per half-open window.
        assert ejector.claim_probe(set()) is None
        # Probe latency back under the ejection threshold: readmitted.
        ejector.observe(replicas[1], 12.0)
        assert replicas[1].state == ms.LIVE
        assert ejector.n_readmitted == 1
        ev = _events(journal, "replica_readmitted")
        assert len(ev) == 1 and ev[0]["replica"] == "r1"

    def test_slow_probe_restarts_the_cooldown(self, journal):
        clock = FakeClock()
        replicas, _, ejector = _member_fleet(
            3, journal, k=3.0, min_samples=4, floor_ms=1.0,
            cooldown_s=5.0, check_interval_s=0.0, clock=clock)
        _feed(ejector, replicas[0], [10.0] * 8)
        _feed(ejector, replicas[2], [10.0] * 8)
        _feed(ejector, replicas[1], [100.0] * 8)
        clock.t += 5.1
        assert ejector.claim_probe(set()) is replicas[1]
        ejector.observe(replicas[1], 90.0)  # still way over threshold
        assert replicas[1].state == ms.DEGRADED
        assert ejector.claim_probe(set()) is None  # cooldown restarted
        clock.t += 5.1
        assert ejector.claim_probe(set()) is replicas[1]
        ejector.observe(replicas[1], 11.0)
        assert replicas[1].state == ms.LIVE
        assert _events(journal, "replica_readmitted")

    def test_pre_ejection_straggler_cannot_short_circuit_readmission(
            self, journal):
        # An in-flight request from before the ejection that completes
        # FAST must not re-admit the replica without a cooldown+probe —
        # whether it drains out inside the cooldown or after it elapsed
        # (only a CLAIMED probe's latency may judge re-admission).
        clock = FakeClock()
        replicas, _, ejector = _member_fleet(
            3, journal, k=3.0, min_samples=4, floor_ms=1.0,
            cooldown_s=5.0, check_interval_s=0.0, clock=clock)
        _feed(ejector, replicas[0], [10.0] * 8)
        _feed(ejector, replicas[2], [10.0] * 8)
        _feed(ejector, replicas[1], [100.0] * 8)
        assert replicas[1].state == ms.DEGRADED
        ejector.observe(replicas[1], 2.0)  # fast straggler drains out
        assert replicas[1].state == ms.DEGRADED
        clock.t += 5.1                     # cooldown elapsed, no probe yet
        ejector.observe(replicas[1], 2.0)  # late fast straggler
        assert replicas[1].state == ms.DEGRADED
        ejector.observe(replicas[1], 400.0)  # late SLOW straggler must
        assert ejector.claim_probe(set()) is replicas[1]  # not re-cooldown
        ejector.observe(replicas[1], 9.0)  # the claimed probe decides
        assert replicas[1].state == ms.LIVE
        assert ejector.n_readmitted == 1

    def test_event_summary_reports_gray_fields(self, journal):
        replicas, _, ejector = _member_fleet(
            3, journal, k=3.0, min_samples=4, floor_ms=1.0,
            cooldown_s=0.0, check_interval_s=0.0)
        _feed(ejector, replicas[0], [10.0] * 8)
        _feed(ejector, replicas[2], [10.0] * 8)
        _feed(ejector, replicas[1], [100.0] * 8)
        assert ejector.claim_probe(set()) is replicas[1]
        ejector.observe(replicas[1], 10.0)
        events = schema.read_events(journal.events_path, complete=False)
        summary = schema.event_summary(events)
        assert summary["replica_ejections"] == 1
        assert summary["replica_readmissions"] == 1
        assert not any("_schema_error" in e for e in events)


# ---------------------------------------------------------------------------
# Ejection drains; it never drops.


class TestEjectionDrain:
    def test_in_flight_requests_on_an_ejected_replica_complete(self,
                                                               journal):
        slow, fast = FakeReplica(), FakeReplica()
        slow.predict_delay = 0.4
        try:
            replicas = [ms.Replica("r0", slow.url, journal=journal),
                        ms.Replica("r1", fast.url, journal=journal)]
            membership = ms.FleetMembership(replicas, journal=journal)
            router = FleetRouter(membership, journal=journal)
            membership.poll_once()
            fast.queue_depth = 50  # force the slow one to be chosen
            membership.poll_once()
            result = {}

            def dispatch():
                result["outcome"] = router.dispatch(b"{}")

            th = threading.Thread(target=dispatch, daemon=True)
            th.start()
            time.sleep(0.1)  # the request is in flight on r0
            assert replicas[0].inflight == 1
            # Eject mid-flight (the exact transition the ejector makes).
            assert membership.set_state(replicas[0], ms.DEGRADED,
                                        "latency_outlier",
                                        only_from=(ms.LIVE,))
            th.join(timeout=5.0)
            assert not th.is_alive()
            status, _, replica_id = result["outcome"]
            # Drained, not dropped: the in-flight request completed on
            # the replica it was already running on.
            assert status == 200 and replica_id == "r0"
            assert replicas[0].state == ms.DEGRADED
            assert replicas[0].inflight == 0
        finally:
            slow.stop()
            fast.stop()


# ---------------------------------------------------------------------------
# Hedged dispatch.


class TestHedging:
    def _warm_window(self, router, n=24):
        for _ in range(n):
            status, _, _ = router.dispatch(b"{}")
            assert status == 200

    def _fleet(self, fakes, journal, hedge):
        replicas = [ms.Replica(f"r{i}", fake.url, journal=journal)
                    for i, fake in enumerate(fakes)]
        membership = ms.FleetMembership(replicas, journal=journal)
        router = FleetRouter(membership, journal=journal, hedge=hedge)
        membership.poll_once()
        return replicas, membership, router

    def test_slow_primary_hedges_to_sibling_and_hedge_wins(self, journal):
        slow, fast = FakeReplica(), FakeReplica()
        try:
            _, membership, router = self._fleet(
                [slow, fast], journal,
                HedgePolicy(quantile=0.9, budget_fraction=0.5,
                            min_samples=8, max_delay_ms=50.0))
            self._warm_window(router, 12)
            # Deltas, not absolutes: with the delay floor at 1ms, a
            # scheduler blip DURING warm-up can legitimately fire a
            # hedge or two — the claim under test is that the slow
            # dispatch fires exactly one more and the hedge wins it.
            hedges_before = router.n_hedges
            wins_before = router.n_hedge_wins
            events_before = len(_events(journal, "hedge"))
            slow.predict_delay = 0.5
            slow.queue_depth, fast.queue_depth = 0, 10  # prefer slow
            membership.poll_once()
            t0 = time.perf_counter()
            status, _, replica_id = router.dispatch(b"{}")
            elapsed = time.perf_counter() - t0
            assert status == 200
            assert replica_id == "r1"          # the hedge answered
            assert elapsed < 0.4               # did NOT wait out the 0.5s
            assert router.n_hedges == hedges_before + 1
            assert router.n_hedge_wins == wins_before + 1
            ev = _events(journal, "hedge")[events_before:]
            assert len(ev) == 1
            assert ev[0]["primary"] == "r0" and ev[0]["hedge"] == "r1"
            assert ev[0]["winner"] == "hedge"
        finally:
            slow.stop()
            fast.stop()

    def test_fast_primary_never_hedges(self, journal):
        a, b = FakeReplica(), FakeReplica()
        try:
            _, _, router = self._fleet(
                [a, b], journal,
                HedgePolicy(budget_fraction=0.5, min_samples=8,
                            min_delay_ms=200.0, max_delay_ms=400.0))
            self._warm_window(router, 30)
            assert router.n_hedges == 0
            assert _events(journal, "hedge") == []
        finally:
            a.stop()
            b.stop()

    def test_hard_budget_caps_extra_dispatches(self, journal):
        slow, fast = FakeReplica(), FakeReplica()
        try:
            _, membership, router = self._fleet(
                [slow, fast], journal,
                HedgePolicy(quantile=0.9, budget_fraction=0.05,
                            min_samples=8, max_delay_ms=30.0))
            self._warm_window(router, 20)
            slow.predict_delay = 0.15
            slow.queue_depth, fast.queue_depth = 0, 10
            membership.poll_once()
            for _ in range(10):
                status, _, _ = router.dispatch(b"{}")
                assert status == 200
            # 30 dispatches at 5%: exactly one hedge may ever fire; the
            # other nine slow requests wait the primary out.
            assert router.n_hedges == 1
            assert router.n_hedges <= 0.05 * router.n_dispatched + 1
            assert len(_events(journal, "hedge")) == 1
        finally:
            slow.stop()
            fast.stop()

    def test_no_hedging_below_min_samples(self, journal):
        slow, fast = FakeReplica(), FakeReplica()
        slow.predict_delay = 0.2
        try:
            _, _, router = self._fleet(
                [slow, fast], journal,
                HedgePolicy(budget_fraction=0.5, min_samples=50,
                            max_delay_ms=10.0))
            fast.queue_depth = 10
            router.membership.poll_once()
            status, _, _ = router.dispatch(b"{}")
            assert status == 200
            assert router.n_hedges == 0  # window too cold to define slow
        finally:
            slow.stop()
            fast.stop()


# ---------------------------------------------------------------------------
# Adaptive AIMD admission + two-class shedding.


class TestAdmission:
    def test_aimd_backoff_and_additive_increase(self, journal):
        clock = FakeClock()
        ctl = AdmissionController(target_wait_ms=10.0, min_limit=8,
                                  max_limit=128, increase=16,
                                  interval_s=1.0, journal=journal,
                                  clock=clock)
        assert ctl.limit == 128  # optimistic start
        for _ in range(5):
            ctl.observe_wait(50.0)
        clock.t += 1.1
        ctl.observe_wait(50.0)   # interval elapsed: p95 50 > 10 -> halve
        assert ctl.limit == 64
        clock.t += 1.1
        ctl.observe_wait(60.0)
        assert ctl.limit == 32
        # Quiet traffic: additive increase, one step per interval.
        for _ in range(3):
            clock.t += 1.1
            ctl.observe_wait(1.0)
        assert ctl.limit == 32 + 3 * 16
        moves = _events(journal, "admission_change")
        assert [m["reason"] for m in moves] == \
            ["backoff", "backoff", "increase", "increase", "increase"]
        assert all(m["target_wait_ms"] == 10.0 for m in moves)

    def test_limit_floors_at_min_and_caps_at_max(self, journal):
        clock = FakeClock()
        ctl = AdmissionController(target_wait_ms=10.0, min_limit=8,
                                  max_limit=32, increase=64,
                                  interval_s=1.0, journal=journal,
                                  clock=clock)
        for _ in range(8):
            clock.t += 1.1
            ctl.observe_wait(100.0)
        assert ctl.limit == 8
        clock.t += 1.1
        ctl.observe_wait(0.5)
        assert ctl.limit == 32  # one big step, clamped to max

    def test_shed_journal_is_throttled_but_counts_every_shed(self,
                                                             journal):
        clock = FakeClock()
        ctl = AdmissionController(target_wait_ms=10.0, min_limit=8,
                                  max_limit=32, journal=journal,
                                  clock=clock)
        for _ in range(100):
            ctl.record_shed()
        clock.t += 1.0
        ctl.record_shed()
        assert ctl.n_shed == 101
        sheds = _events(journal, "shed")
        assert len(sheds) == 2  # first + one throttled flush
        assert sum(e["n_shed"] for e in sheds) == 101

    def test_bulk_sheds_first_priority_only_hits_the_hard_cliff(
            self, journal):
        release = threading.Event()
        started = threading.Event()

        def blocking_infer(x):
            started.set()
            release.wait(10.0)
            return np.zeros(len(x), np.int64)

        ctl = AdmissionController(target_wait_ms=10.0, min_limit=4,
                                  max_limit=16, journal=journal,
                                  clock=FakeClock())
        batcher = MicroBatcher(blocking_infer, max_batch=1,
                               max_wait_ms=0.0, max_queue_trials=64,
                               journal=journal, admission=ctl)
        try:
            one = np.zeros((1, 2, 4), np.float32)
            batcher.submit(one)         # dequeued by the blocked worker
            started.wait(5.0)
            futs = [batcher.submit(one) for _ in range(16)]  # at limit
            with pytest.raises(Shed):
                batcher.submit(one)     # bulk #17: shed by policy
            assert ctl.n_shed == 1
            # Priority traffic sails past the adaptive limit...
            pfuts = [batcher.submit(one, priority=True)
                     for _ in range(16)]
            # ...and only the HARD queue bound stops it.
            extra = [batcher.submit(one, priority=True)
                     for _ in range(64 - 32)]
            with pytest.raises(Rejected) as exc_info:
                batcher.submit(one, priority=True)
            assert not isinstance(exc_info.value, Shed)
            release.set()
            for fut in futs + pfuts + extra:
                fut.result(timeout=10.0)
        finally:
            release.set()
            batcher.close(drain=False)


# ---------------------------------------------------------------------------
# The end-to-end acceptance: real engines, real degraded load.


class TestGrayBenchSelftest:
    def test_gray_selftest_passes(self, tmp_path):
        """ISSUE-10 acceptance: (a) one replica degraded to >= 20x
        forward latency is ejected while hedging holds open-loop p99
        within 2x the healthy baseline at zero failures, and is
        readmitted once the fault lifts (journaled in order); (b) at 2x
        saturation, AIMD admission keeps on-time goodput >= 70% of peak
        while the static cliff collapses, shedding bulk before priority
        traffic every time."""
        out = tmp_path / "BENCH_GRAY_selftest.json"
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "serve_bench.py"),
             "--gray", "--selftest", "--grayOut", str(out),
             "--workDir", str(tmp_path / "work")],
            capture_output=True, text=True, timeout=420,
            env=dict(os.environ, EEGTPU_NO_LOG_FILE="1",
                     EEGTPU_PLATFORM="cpu"))
        assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
        assert "SELFTEST PASS" in proc.stdout
        record = json.loads(out.read_text())
        slow = record["slow_replica_leg"]
        assert slow["gray"]["failures"] == 0
        assert slow["degrade_factor"] >= 20.0
        assert slow["p99_ratio"] <= 2.0
        assert slow["ejections"] >= 1
        assert slow["victim_readmitted"] is True
        assert slow["hedge_fraction"] <= 0.05
        over = record["overload_leg"]
        assert over["adaptive_goodput_frac"] >= 0.7
        assert over["adaptive"]["shed_priority"] == 0
        assert over["adaptive"]["shed_bulk"] > 0
        assert record["journal"]["ejected_before_readmitted"] is True
        assert math.isfinite(over["static_goodput_frac"])
