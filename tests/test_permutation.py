"""Permutation-test behavior on separable synthetic data."""

import unittest

import numpy as np

from eegnetreplication_tpu.config import DEFAULT_TRAINING
from eegnetreplication_tpu.training.permutation import permutation_test
from tests.synthetic import synthetic_subject


class TestPermutationTest(unittest.TestCase):
    def test_real_beats_null_on_separable_data(self):
        d = synthetic_subject(1, "Train", n_trials=96, n_channels=8,
                              n_times=64, class_sep=2.0)
        cfg = DEFAULT_TRAINING.replace(batch_size=32)
        result = permutation_test(d.X, d.y, n_permutations=4, epochs=12,
                                  config=cfg, seed=0)
        # Strongly separable classes: the real run must clear the null.
        self.assertGreater(result.real_accuracy, 50.0)
        self.assertEqual(len(result.permuted_accuracies), 4)
        self.assertLess(result.mean_permuted, result.real_accuracy)
        self.assertLessEqual(result.p_value, 0.5)

    def test_p_value_range(self):
        d = synthetic_subject(2, "Train", n_trials=48, n_channels=4,
                              n_times=32, class_sep=0.0)  # pure noise
        cfg = DEFAULT_TRAINING.replace(batch_size=16)
        result = permutation_test(d.X, d.y, n_permutations=3, epochs=3,
                                  config=cfg, seed=1)
        self.assertGreaterEqual(result.p_value, 1 / 4)
        self.assertLessEqual(result.p_value, 1.0)


if __name__ == "__main__":
    unittest.main()
