"""Model wiring/shape/gradient tests.

Mirrors the reference's synthetic-tensor model suite
(``tests/test_model.py:21-185``) under JAX: structure of the parameter tree,
output shapes across batch sizes and (C, T) combinations, dtype, and gradient
presence after one backward pass.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from eegnetreplication_tpu.models import (
    DeepConvNet,
    EEGNet,
    ShallowConvNet,
    eegnet_wide,
    get_model,
)


def init_model(model, C=22, T=257, batch=2, seed=0):
    x = jnp.zeros((batch, C, T), jnp.float32)
    variables = model.init(jax.random.PRNGKey(seed), x, train=False)
    return variables, x


class TestEEGNetStructure:
    def test_parameter_tree_layers(self):
        model = EEGNet()
        variables, _ = init_model(model)
        params = variables["params"]
        assert set(params) == {
            "temporal_conv", "temporal_bn", "spatial_conv", "spatial_bn",
            "separable_depthwise", "separable_pointwise", "block2_bn",
            "classifier",
        }

    def test_kernel_shapes_default(self):
        variables, _ = init_model(EEGNet())
        p = variables["params"]
        # Flax NHWC kernels: (kh, kw, in/groups, out).
        assert p["temporal_conv"]["kernel"].shape == (1, 32, 1, 8)
        assert p["spatial_conv"]["kernel"].shape == (22, 1, 1, 16)
        assert p["separable_depthwise"]["kernel"].shape == (1, 16, 1, 16)
        assert p["separable_pointwise"]["kernel"].shape == (1, 1, 16, 16)
        assert p["classifier"]["kernel"].shape == (16 * 8, 4)
        assert p["classifier"]["bias"].shape == (4,)

    def test_no_conv_bias(self):
        variables, _ = init_model(EEGNet())
        for layer in ("temporal_conv", "spatial_conv", "separable_depthwise",
                      "separable_pointwise"):
            assert "bias" not in variables["params"][layer]

    def test_custom_f1_d_wiring(self):
        model = EEGNet(F1=4, D=3)
        variables, _ = init_model(model)
        p = variables["params"]
        assert p["temporal_conv"]["kernel"].shape == (1, 32, 1, 4)
        assert p["spatial_conv"]["kernel"].shape == (22, 1, 1, 12)
        assert p["classifier"]["kernel"].shape == (12 * 8, 4)

    def test_wide_variant(self):
        model = eegnet_wide()
        assert model.F1 == 16 and model.D == 4 and model.F2 == 64

    def test_batch_stats_collection_exists(self):
        variables, _ = init_model(EEGNet())
        assert set(variables["batch_stats"]) == {
            "temporal_bn", "spatial_bn", "block2_bn"
        }

    def test_param_count_matches_reference_scale(self):
        variables, _ = init_model(EEGNet())
        n = sum(x.size for x in jax.tree_util.tree_leaves(variables["params"]))
        # conv kernels 256+352+256+256, BN 16+32+32, classifier 516 = 1716
        assert n == 1716


class TestEEGNetBehavior:
    @pytest.mark.parametrize("batch", [1, 2, 7, 64])
    def test_output_shape_batches(self, batch):
        model = EEGNet()
        variables, _ = init_model(model)
        x = jnp.zeros((batch, 22, 257))
        out = model.apply(variables, x, train=False)
        assert out.shape == (batch, 4)

    @pytest.mark.parametrize("C,T", [(22, 257), (22, 256), (10, 128), (3, 64)])
    def test_output_shape_ct(self, C, T):
        model = EEGNet(n_channels=C, n_times=T)
        variables, _ = init_model(model, C=C, T=T)
        out = model.apply(variables, jnp.zeros((5, C, T)), train=False)
        assert out.shape == (5, 4)

    def test_wrong_input_shape_raises(self):
        model = EEGNet()
        variables, _ = init_model(model)
        with pytest.raises(ValueError, match="Expected input"):
            model.apply(variables, jnp.zeros((2, 21, 257)), train=False)

    def test_output_dtype_float32(self):
        variables, _ = init_model(EEGNet())
        out = EEGNet().apply(variables, jnp.zeros((2, 22, 257)), train=False)
        assert out.dtype == jnp.float32

    def test_logits_not_softmaxed(self):
        variables, x = init_model(EEGNet())
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 22, 257))
        out = EEGNet().apply(variables, x, train=False)
        sums = jnp.sum(jax.nn.softmax(out, axis=1), axis=1)
        np.testing.assert_allclose(np.asarray(sums), 1.0, rtol=1e-5)
        assert not np.allclose(np.asarray(jnp.sum(out, axis=1)), 1.0)

    def test_gradients_nonzero_everywhere(self):
        model = EEGNet()
        variables, _ = init_model(model)
        x = jax.random.normal(jax.random.PRNGKey(2), (8, 22, 257))
        y = jnp.array([0, 1, 2, 3, 0, 1, 2, 3])

        def loss_fn(params):
            logits, _ = model.apply(
                {"params": params, "batch_stats": variables["batch_stats"]},
                x, train=True, rngs={"dropout": jax.random.PRNGKey(3)},
                mutable=["batch_stats"],
            )
            onehot = jax.nn.one_hot(y, 4)
            return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), axis=1))

        grads = jax.grad(loss_fn)(variables["params"])
        for path, g in jax.tree_util.tree_leaves_with_path(grads):
            assert np.all(np.isfinite(np.asarray(g))), path
            assert float(jnp.max(jnp.abs(g))) > 0.0, path

    def test_dropout_stochastic_in_train_mode(self):
        model = EEGNet(dropout_rate=0.5)
        variables, _ = init_model(model)
        x = jax.random.normal(jax.random.PRNGKey(4), (4, 22, 257))
        outs = []
        for seed in (0, 1):
            out, _ = model.apply(
                variables, x, train=True,
                rngs={"dropout": jax.random.PRNGKey(seed)},
                mutable=["batch_stats"],
            )
            outs.append(np.asarray(out))
        assert not np.allclose(outs[0], outs[1])

    def test_eval_mode_deterministic(self):
        model = EEGNet()
        variables, _ = init_model(model)
        x = jax.random.normal(jax.random.PRNGKey(5), (4, 22, 257))
        a = model.apply(variables, x, train=False)
        b = model.apply(variables, x, train=False)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestConvNets:
    @pytest.mark.parametrize("cls", [ShallowConvNet, DeepConvNet])
    def test_forward_shape(self, cls):
        model = cls()
        variables, _ = init_model(model)
        out = model.apply(variables, jnp.zeros((3, 22, 257)), train=False)
        assert out.shape == (3, 4)

    @pytest.mark.parametrize("cls", [ShallowConvNet, DeepConvNet])
    def test_train_mode_runs(self, cls):
        model = cls()
        variables, _ = init_model(model)
        x = jax.random.normal(jax.random.PRNGKey(6), (4, 22, 257))
        out, updates = model.apply(
            variables, x, train=True,
            rngs={"dropout": jax.random.PRNGKey(0)}, mutable=["batch_stats"],
        )
        assert out.shape == (4, 4)
        assert np.all(np.isfinite(np.asarray(out)))


class TestRegistry:
    def test_lookup(self):
        model = get_model("eegnet", F1=4)
        assert isinstance(model, EEGNet) and model.F1 == 4

    def test_unknown_raises(self):
        with pytest.raises(ValueError, match="Unknown model"):
            get_model("transformer9000")
