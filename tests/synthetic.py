"""Synthetic BCI-IV-2a-shaped data for tests (no real data in CI, like the
reference's all-synthetic test suite, SURVEY.md §4)."""

import numpy as np

from eegnetreplication_tpu.data.containers import BCICI2ADataset


def synthetic_subject(subject: int, mode: str, n_trials: int = 48,
                      n_channels: int = 8, n_times: int = 64,
                      class_sep: float = 1.0) -> BCICI2ADataset:
    """Deterministic per-subject dataset with class-dependent sinusoids."""
    seed = subject * 100 + (0 if mode == "Train" else 1)
    rng = np.random.RandomState(seed)
    t = np.arange(n_times) / 64.0
    y = rng.randint(0, 4, size=n_trials)
    X = rng.randn(n_trials, n_channels, n_times).astype(np.float32) * 0.5
    for k in range(4):
        sig = class_sep * np.sin(2 * np.pi * (4.0 + 4.0 * k) * t)
        X[y == k] += sig[None, None, :].astype(np.float32)
    return BCICI2ADataset(X=X, y=y.astype(np.int64))


def make_loader(n_trials=48, n_channels=8, n_times=64, class_sep=1.0):
    def loader(subject: int, mode: str) -> BCICI2ADataset:
        return synthetic_subject(subject, mode, n_trials=n_trials,
                                 n_channels=n_channels, n_times=n_times,
                                 class_sep=class_sep)

    return loader
