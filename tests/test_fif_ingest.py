"""The MNE-gated ``.fif`` ingest branches, executed in CI (VERDICT r2
item 9).

Without MNE these paths (``epoching.py::build_dataset_from_fif_dir``,
``moabb.py::load_moabb_run``) were import-gated dead code here; the
``fake_mne`` double supplies the API slice they touch, so the branch
logic — annotation-id selection, TrueLabels alignment via
``Epochs.selection``, the V->uV conversion and EOG drop — now runs in CI.
(With a real MNE installed these double-backed fixtures skip; the payload
format is the double's.)
"""

import importlib.util

import numpy as np
import pytest
from scipy import io as scipy_io

from eegnetreplication_tpu.config import Paths

SFREQ = 128.0  # -> 0.5..2.5 s inclusive = samples 64..320 = 257


@pytest.fixture(autouse=True)
def mne_double():
    """Install the MNE double (these fixtures write its .npz-backed
    payloads, which a real MNE could not parse)."""
    if importlib.util.find_spec("mne") is not None:
        pytest.skip("real MNE installed; the .fif branches are exercised "
                    "directly against it elsewhere — these tests drive the "
                    "fake_mne double")
    import fake_mne

    fake_mne.install()
    yield
    fake_mne.uninstall()


def _write_session(path, descs, onsets_s, n_ch=3, n_samples=3000,
                   seed=0, scale=1.0):
    import fake_mne

    rng = np.random.RandomState(seed)
    data = rng.randn(n_ch, n_samples) * scale
    fake_mne.write_fake_fif(
        path, data, SFREQ, [f"EEG{i}" for i in range(n_ch)],
        onsets_s, descs)
    return data


class TestBuildDatasetFromFifDir:
    def test_train_session_selects_cue_descriptions(self, tmp_path):
        from eegnetreplication_tpu.data.epoching import (
            build_dataset_from_fif_dir,
        )

        # four cues plus a non-cue annotation that must be ignored
        _write_session(tmp_path / "A01T-preprocessed.fif",
                       ["769", "770", "771", "772", "768"],
                       [2.0, 5.0, 8.0, 11.0, 1.9])
        ds = build_dataset_from_fif_dir(
            tmp_path, subject="1", mode="Train",
            paths=Paths.from_root(tmp_path))
        assert ds.X.shape == (4, 3, 257)
        assert list(ds.y) == [0, 1, 2, 3]

    def test_eval_session_aligns_true_labels_via_selection(self, tmp_path):
        from eegnetreplication_tpu.data.epoching import (
            build_dataset_from_fif_dir,
        )

        paths = Paths.from_root(tmp_path)
        # five unknown-cue trials; the last one's window falls off the
        # recording end and must drop WITH its label (selection semantics)
        _write_session(tmp_path / "A01E-preprocessed.fif",
                       ["783"] * 5, [2.0, 5.0, 8.0, 11.0, 22.0])
        labels_dir = paths.data_raw / "TrueLabels"
        labels_dir.mkdir(parents=True)
        scipy_io.savemat(labels_dir / "A01E.mat",
                         {"classlabel": np.array([1, 2, 3, 4, 1])})
        ds = build_dataset_from_fif_dir(tmp_path, subject="1", mode="Eval",
                                        paths=paths)
        assert ds.X.shape == (4, 3, 257)
        assert list(ds.y) == [0, 1, 2, 3]  # 5th label dropped with trial

    def test_missing_files_raise(self, tmp_path):
        from eegnetreplication_tpu.data.epoching import (
            build_dataset_from_fif_dir,
        )

        with pytest.raises(ValueError, match="No .fif files"):
            build_dataset_from_fif_dir(tmp_path, subject="1", mode="Train",
                                       paths=Paths.from_root(tmp_path))


class TestLoadMoabbRun:
    def test_run_loads_with_uv_scaling_and_eog_drop(self, tmp_path):
        import fake_mne

        from eegnetreplication_tpu.data.moabb import load_moabb_run

        rng = np.random.RandomState(1)
        data_v = rng.randn(3, 2000) * 1e-5  # volts, MNE-style
        path = tmp_path / "run_0.fif"
        fake_mne.write_fake_fif(
            path, data_v, 250.0, ["C3", "C4", "EOG1"],
            [1.0, 3.0, 5.0], ["left_hand", "tongue", "garbage"],
            ch_types=["eeg", "eeg", "eog"])
        rec = load_moabb_run(path)
        assert rec.signals.shape == (2, 2000)  # EOG dropped
        np.testing.assert_allclose(rec.signals,
                                   (data_v[:2] * 1e6).astype(np.float32))
        assert list(rec.event_typ) == [769, 772]  # garbage desc ignored
        assert list(rec.event_pos) == [250, 750]
        assert rec.labels == ["C3", "C4"]
