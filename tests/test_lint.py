"""eegtpu-lint tests: the whole-tree tier-1 gate plus per-pass fixtures.

Two layers:

- **Gate** — all passes over the real ``eegnetreplication_tpu/`` +
  ``scripts/`` tree must produce zero non-baseline findings and zero
  stale baseline entries, in under 10 s (the linter is a tier-1
  pre-stage; it must stay cheap).
- **Fixtures** — per rule, a bad snippet the pass must catch and a good
  snippet it must not, including re-introductions of the two bug shapes
  that motivated the linter: the PR-10 hand-spelled passthrough-header
  set (dropped ``X-Model``) and the PR-11 unknown-child-flag
  argparse-exit (``--resume`` appended to an entry point that does not
  accept it).
"""

import json
import time
from pathlib import Path

import pytest

from eegnetreplication_tpu.analysis import (
    Contracts,
    Project,
    apply_baseline,
    cli,
    inject_sites,
    jit_purity,
    journal_events,
    load_baseline,
    lock_discipline,
    run_all,
    single_source,
    spawn_args,
)

REPO = Path(__file__).resolve().parents[1]

# Mini single-sourced contract files every fixture tree starts from.
SCHEMA_SRC = '''\
EVENT_REQUIRED = {
    "thing_done": ("a", "b"),
    "ghost_event": ("x",),
}


def event_summary(events):
    return [e for e in events if e["event"] == "thing_done"
            or e["event"] == "ghost_event"]
'''

INJECT_SRC = '''\
SITES = ("good.site", "other.site")


class FaultSpec:
    site: str
    after: int = 0
    times: int = 1
    sleep: float | None = None


def fire(site, **ctx):
    pass


def arm(spec, **options):
    pass


def parse_plan(text):
    pass
'''

SERVICE_SRC = '''\
PASSTHROUGH_HEADERS = ("X-Model", "X-Deadline-Ms", "X-Priority")
'''

BENCH_NOTES_SRC = "Documented here: thing_done and ghost_event.\n"


def make_project(tmp_path, files, bench_notes=BENCH_NOTES_SRC):
    """A fixture tree with the contract skeleton plus ``files``."""
    base = {
        "eegnetreplication_tpu/obs/schema.py": SCHEMA_SRC,
        "eegnetreplication_tpu/resil/inject.py": INJECT_SRC,
        "eegnetreplication_tpu/serve/service.py": SERVICE_SRC,
    }
    base.update(files)
    for rel, src in base.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    (tmp_path / "BENCH_NOTES.md").write_text(bench_notes)
    project = Project.scan(tmp_path)
    return project, Contracts.from_project(project)


def rules_for(findings, rel=None):
    return [(f.rule, f.symbol) for f in findings
            if rel is None or f.file == rel]


class TestLintGate:
    """The tier-1 contract: the real tree is clean and the linter cheap."""

    def test_whole_tree_zero_non_baseline_findings(self):
        t0 = time.monotonic()
        findings = run_all(REPO)
        baseline = load_baseline(REPO / "lint_baseline.json")
        new, matched, stale = apply_baseline(findings, baseline)
        wall = time.monotonic() - t0
        assert not new, "new lint findings:\n" + "\n".join(
            f.render() for f in new)
        assert not stale, ("stale baseline entries (issue fixed — delete "
                           f"them, baselines only shrink): {stale}")
        # The baseline is exceptions-only: every entry must carry a
        # justification.
        for entry in baseline.values():
            assert entry.get("why"), f"baseline entry without why: {entry}"
        # Tier-1 pre-stage budget: the whole-package run stays cheap.
        assert wall < 10.0, f"lint took {wall:.1f}s (budget 10s)"

    def test_cli_json_schema(self, capsys):
        rc = cli.main(["--root", str(REPO), "--json"])
        record = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert record["schema_version"] == 1
        assert set(record["counts"]) == {"total", "new", "baselined",
                                         "stale_baseline"}
        assert record["counts"]["new"] == 0
        for f in record["findings"]:
            assert set(f) == {"rule", "file", "line", "symbol", "message",
                              "severity", "baselined"}


class TestJournalEventsPass:
    def test_unknown_event_type_caught(self, tmp_path):
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'def f(jr):\n    jr.event("thing_dome", a=1, b=2)\n'})
        found = rules_for(journal_events.check(project, contracts),
                          "eegnetreplication_tpu/mod.py")
        assert ("journal-event-unknown", "thing_dome") in found

    def test_missing_required_keys_caught(self, tmp_path):
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'def f(jr):\n    jr.event("thing_done", a=1)\n'})
        found = rules_for(journal_events.check(project, contracts),
                          "eegnetreplication_tpu/mod.py")
        assert ("journal-event-missing-keys", "thing_done") in found

    def test_good_call_and_splat_not_flagged(self, tmp_path):
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'def f(jr, payload):\n'
                '    jr.event("thing_done", a=1, b=2)\n'
                '    jr.event("ghost_event", **payload)\n'})
        assert not rules_for(journal_events.check(project, contracts),
                             "eegnetreplication_tpu/mod.py")

    def test_unemitted_undocumented_unsummarized(self, tmp_path):
        # Only thing_done is emitted; ghost_event is declared + summarized
        # + documented, dead_event is declared and invisible everywhere.
        schema = SCHEMA_SRC.replace(
            '"ghost_event": ("x",),',
            '"ghost_event": ("x",),\n    "dead_event": (),')
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/obs/schema.py": schema,
            "eegnetreplication_tpu/mod.py":
                'def f(jr):\n    jr.event("thing_done", a=1, b=2)\n'
                'def g(jr):\n    jr.event("ghost_event", x=1)\n'})
        found = rules_for(journal_events.check(project, contracts))
        assert ("journal-event-unemitted", "dead_event") in found
        assert ("journal-event-undocumented", "dead_event") in found
        assert ("journal-event-unsummarized", "dead_event") in found
        assert ("journal-event-unemitted", "ghost_event") not in found
        assert ("journal-event-undocumented", "thing_done") not in found

    def test_missing_event_summary_is_loud(self, tmp_path):
        # A renamed/moved event_summary must not silently kill the
        # unsummarized rule (and stale out the whole baseline).
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/obs/schema.py":
                'EVENT_REQUIRED = {\n    "thing_done": ("a", "b"),\n}\n',
            "eegnetreplication_tpu/mod.py":
                'def f(jr):\n    jr.event("thing_done", a=1, b=2)\n'})
        found = rules_for(journal_events.check(project, contracts))
        assert ("contract-missing", "event_summary") in found
        assert ("journal-event-unsummarized", "thing_done") not in found

    def test_missing_bench_notes_is_loud(self, tmp_path):
        # An absent/empty BENCH_NOTES.md must surface as one contract-
        # missing finding, not silently disable the undocumented rule.
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'def f(jr):\n    jr.event("thing_done", a=1, b=2)\n'},
            bench_notes="")
        found = rules_for(journal_events.check(project, contracts))
        assert ("contract-missing", "BENCH_NOTES.md") in found

    @pytest.mark.parametrize("decl", ['MEMBER_EVENT = "ghost_event"',
                                      'MEMBER_EVENT: str = "ghost_event"'])
    def test_member_event_class_attr_counts_as_emission(self, tmp_path,
                                                        decl):
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'class M:\n'
                f'    {decl}\n'
                'def f(jr):\n    jr.event("thing_done", a=1, b=2)\n'})
        found = rules_for(journal_events.check(project, contracts))
        assert ("journal-event-unemitted", "ghost_event") not in found

    def test_suppression_comment_silences_line(self, tmp_path):
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'def f(jr):\n'
                '    jr.event("odd_one")  '
                '# lint: ignore[journal-event-unknown]\n'})
        from eegnetreplication_tpu.analysis.core import filter_suppressed
        findings = filter_suppressed(
            project, journal_events.check(project, contracts))
        assert ("journal-event-unknown", "odd_one") not in rules_for(findings)


class TestInjectSitesPass:
    def test_bad_fire_and_faultspec_site_caught(self, tmp_path):
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'from eegnetreplication_tpu.resil.inject import '
                'FaultSpec, fire\n'
                'def f():\n'
                '    fire("good.site")\n'
                '    fire("bad.site")\n'
                '    FaultSpec(site="also.bad")\n'})
        found = rules_for(inject_sites.check(project, contracts),
                          "eegnetreplication_tpu/mod.py")
        assert ("inject-site-unknown", "bad.site") in found
        assert ("inject-site-unknown", "also.bad") in found
        assert ("inject-site-unknown", "good.site") not in found

    def test_unrelated_local_arm_not_flagged(self, tmp_path):
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'def arm(name):\n    pass\n'
                'def f():\n    arm("not.a.site")\n'})
        assert not rules_for(inject_sites.check(project, contracts),
                             "eegnetreplication_tpu/mod.py")

    def test_chaos_plan_literals_checked(self, tmp_path):
        project, contracts = make_project(tmp_path, {
            "scripts/drill.py":
                'cmd = ["x", "--chaos",\n'
                '       "good.site:times=1,bad.site:after=2"]\n'
                'def run(child):\n'
                '    child(chaos="good.site:tmies=1")\n'})
        found = rules_for(inject_sites.check(project, contracts),
                          "scripts/drill.py")
        assert ("chaos-plan-unknown-site", "bad.site") in found
        assert ("chaos-plan-unknown-option", "good.site:tmies") in found
        assert ("chaos-plan-unknown-site", "good.site") not in found

    def test_keyword_form_fire_checked_and_probes(self, tmp_path):
        # fire(site="...") is a legal call shape (fire's signature is
        # fire(site, **ctx)); the keyword form must be checked and earn
        # probe credit exactly like the positional one.
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'from eegnetreplication_tpu.resil.inject import fire\n'
                'def f():\n'
                '    fire(site="bad.site")\n'
                '    fire(site="good.site")\n'
                '    fire(site="other.site")\n'})
        found = rules_for(inject_sites.check(project, contracts))
        assert ("inject-site-unknown", "bad.site") in found
        assert ("inject-site-unprobed", "good.site") not in found
        assert ("inject-site-unprobed", "other.site") not in found

    def test_unrelated_site_kwarg_is_not_probe_credit(self, tmp_path):
        # retry policies / journal events carry site= labels too; those
        # must not mask dead-site detection.
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'from eegnetreplication_tpu.resil.inject import fire\n'
                'def f(retry):\n'
                '    fire("good.site")\n'
                '    retry.call(lambda: 0, site="other.site")\n'})
        found = rules_for(inject_sites.check(project, contracts))
        assert ("inject-site-unprobed", "other.site") in found

    def test_dead_site_detection_and_site_default_probe(self, tmp_path):
        # good.site is fired directly; other.site only through a probe
        # wrapper's site= default (the _armed_dispatch idiom — the body
        # fires the param, which is what makes the default a probe).
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'from eegnetreplication_tpu.resil.inject import fire\n'
                'def f():\n    fire("good.site")\n'
                'def wrap(fn, site="other.site"):\n'
                '    fire(site)\n    return fn\n'
                'def labeled(fn, site="not.a.site"):\n'
                '    return fn\n'})  # label namespace: no fire -> ignored
        found = rules_for(inject_sites.check(project, contracts))
        assert ("inject-site-unprobed", "other.site") not in found
        assert ("inject-site-unprobed", "good.site") not in found
        assert ("inject-site-unknown", "not.a.site") not in found
        # Drop the default-probe wrapper: other.site goes dead.
        project2, contracts2 = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'from eegnetreplication_tpu.resil.inject import fire\n'
                'def f():\n    fire("good.site")\n'})
        found2 = rules_for(inject_sites.check(project2, contracts2))
        assert ("inject-site-unprobed", "other.site") in found2


CHILD_SRC = '''\
import argparse


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--checkpoint", required=True)
    parser.add_argument("--port", type=int)
    return 0
'''


class TestSpawnArgsPass:
    def test_pr11_unknown_child_flag_caught(self, tmp_path):
        # The PR-11 shape: a relaunch policy appends --resume to a child
        # whose argparse does not accept it (argparse exits 2 -> the
        # supervisor retires the child permanently).
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/childmod.py": CHILD_SRC,
            "eegnetreplication_tpu/spawner.py":
                'import sys\n'
                'def spawn(SupervisorPolicy):\n'
                '    cmd = [sys.executable, "-m",\n'
                '           "eegnetreplication_tpu.childmod",\n'
                '           "--checkpoint", "x.npz"]\n'
                '    policy = SupervisorPolicy(resume_arg="--resume")\n'
                '    return cmd, policy\n'})
        found = rules_for(spawn_args.check(project, contracts),
                          "eegnetreplication_tpu/spawner.py")
        assert ("spawn-arg-unknown", "--resume") in found
        assert ("spawn-arg-unknown", "--checkpoint") not in found

    def test_unknown_literal_flag_in_cmd_list_caught(self, tmp_path):
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/childmod.py": CHILD_SRC,
            "scripts/bench.py":
                'import sys\n'
                'def run():\n'
                '    cmd = [sys.executable, "-m",\n'
                '           "eegnetreplication_tpu.childmod",\n'
                '           "--port", "80"]\n'
                '    cmd += ["--chekpoint", "x.npz"]\n'
                '    cmd.append("--verbose")\n'
                '    return cmd\n'})
        found = rules_for(spawn_args.check(project, contracts),
                          "scripts/bench.py")
        assert ("spawn-arg-unknown", "--chekpoint") in found
        assert ("spawn-arg-unknown", "--verbose") in found
        assert ("spawn-arg-unknown", "--port") not in found

    def test_reassigned_cmd_var_first_spawn_still_checked(self, tmp_path):
        # cmd = [...bad...]; run(cmd); cmd = [...ok...] — rebuilding the
        # variable must not un-check the first command.
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/childmod.py": CHILD_SRC,
            "scripts/bench.py":
                'import sys, subprocess\n'
                'def run():\n'
                '    cmd = [sys.executable, "-m",\n'
                '           "eegnetreplication_tpu.childmod", "--badflag"]\n'
                '    subprocess.run(cmd)\n'
                '    cmd = [sys.executable, "-m",\n'
                '           "eegnetreplication_tpu.childmod",\n'
                '           "--port", "0"]\n'
                '    subprocess.run(cmd)\n'})
        found = rules_for(spawn_args.check(project, contracts),
                          "scripts/bench.py")
        assert ("spawn-arg-unknown", "--badflag") in found
        assert ("spawn-arg-unknown", "--port") not in found

    def test_inline_concat_expression_checked(self, tmp_path):
        # subprocess.run(cmd + ["--flag"]) and ([...] + [...]) — concat
        # at expression position must not lose the target.
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/childmod.py": CHILD_SRC,
            "scripts/bench.py":
                'import sys, subprocess\n'
                'def run():\n'
                '    cmd = [sys.executable, "-m",\n'
                '           "eegnetreplication_tpu.childmod"]\n'
                '    subprocess.run(cmd + ["--inlineBad"])\n'
                '    subprocess.run([sys.executable, "-m",\n'
                '                    "eegnetreplication_tpu.childmod"]\n'
                '                   + ["--alsoBad", "--port", "1"])\n'})
        found = rules_for(spawn_args.check(project, contracts),
                          "scripts/bench.py")
        assert ("spawn-arg-unknown", "--inlineBad") in found
        assert ("spawn-arg-unknown", "--alsoBad") in found
        assert ("spawn-arg-unknown", "--port") not in found

    def test_self_referential_extend_keeps_tracking(self, tmp_path):
        # cmd = [...]; cmd = cmd + ["--flag"] — the natural way to
        # extend a command line must inherit the target.
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/childmod.py": CHILD_SRC,
            "scripts/bench.py":
                'import sys\n'
                'def run():\n'
                '    cmd = [sys.executable, "-m",\n'
                '           "eegnetreplication_tpu.childmod"]\n'
                '    cmd = cmd + ["--nope"]\n'
                '    cmd = cmd + ["--port", "0"]\n'
                '    return cmd\n'})
        found = rules_for(spawn_args.check(project, contracts),
                          "scripts/bench.py")
        assert ("spawn-arg-unknown", "--nope") in found
        assert ("spawn-arg-unknown", "--port") not in found

    def test_py_suffixed_flag_value_does_not_retarget(self, tmp_path):
        # ["scripts/x.py", "--plan", <anything ending .py>, "--bad"] —
        # a flag's value must not steal the target, or the flags after
        # it silently escape checking.
        project, contracts = make_project(tmp_path, {
            "scripts/target.py":
                'import argparse\n'
                'def main():\n'
                '    p = argparse.ArgumentParser()\n'
                '    p.add_argument("--plan")\n'
                '    p.add_argument("--ok")\n',
            "scripts/caller.py":
                'import sys\n'
                'def run(root):\n'
                '    cmd = [sys.executable, "scripts/target.py",\n'
                '           "--plan", str(root / "chaos.py"),\n'
                '           "--bad", "1"]\n'
                '    return cmd\n'})
        found = rules_for(spawn_args.check(project, contracts),
                          "scripts/caller.py")
        assert ("spawn-arg-unknown", "--bad") in found
        assert ("spawn-arg-unknown", "--plan") not in found

    def test_augassign_to_untracked_var_still_scanned(self, tmp_path):
        # cmd = list(base); cmd += ["python", "scripts/x.py", "--bad"] —
        # the augmented literal carries its own target and must not be
        # swallowed just because `cmd` itself is untracked.
        project, contracts = make_project(tmp_path, {
            "scripts/target.py":
                'import argparse\n'
                'def main():\n'
                '    p = argparse.ArgumentParser()\n'
                '    p.add_argument("--ok")\n',
            "scripts/caller.py":
                'def run(base):\n'
                '    cmd = list(base)\n'
                '    cmd += ["python", "scripts/target.py", "--bad"]\n'
                '    return cmd\n'})
        found = rules_for(spawn_args.check(project, contracts),
                          "scripts/caller.py")
        assert ("spawn-arg-unknown", "--bad") in found

    def test_separator_retargets_and_unknown_targets_skipped(self, tmp_path):
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/childmod.py": CHILD_SRC,
            "scripts/outer.py":
                'import argparse, sys\n'
                'def main():\n'
                '    p = argparse.ArgumentParser()\n'
                '    p.add_argument("--graceS")\n'
                'def run():\n'
                '    cmd = [sys.executable, "outer.py", "--graceS", "5",\n'
                '           "--", sys.executable, "-m",\n'
                '           "eegnetreplication_tpu.childmod",\n'
                '           "--prot", "x"]\n'
                '    other = ["git", "--no-pager", "log"]\n'
                '    return cmd, other\n'})
        found = rules_for(spawn_args.check(project, contracts),
                          "scripts/outer.py")
        assert ("spawn-arg-unknown", "--prot") in found
        assert ("spawn-arg-unknown", "--graceS") not in found
        # No resolvable target -> never guess, never flag.
        assert ("spawn-arg-unknown", "--no-pager") not in found

    def test_bare_literal_script_path_sets_target(self, tmp_path):
        # ["python", "scripts/x.py", "--flag"] — the most common spelling
        # must resolve the target just like the Path-expression form.
        project, contracts = make_project(tmp_path, {
            "scripts/target.py":
                'import argparse\n'
                'def main():\n'
                '    p = argparse.ArgumentParser()\n'
                '    p.add_argument("--ok")\n',
            "scripts/caller.py":
                'import subprocess\n'
                'def run():\n'
                '    subprocess.run(["python", "scripts/target.py",\n'
                '                    "--ok", "1", "--bogus"])\n'})
        found = rules_for(spawn_args.check(project, contracts),
                          "scripts/caller.py")
        assert ("spawn-arg-unknown", "--bogus") in found
        assert ("spawn-arg-unknown", "--ok") not in found

    def test_serve_args_seam_checked(self, tmp_path):
        # spawn_replica_fleet(serve_args=...) flags target the serve
        # entry point even though the list itself names no module.
        service = SERVICE_SRC + CHILD_SRC
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/serve/service.py": service,
            "eegnetreplication_tpu/serve/__main__.py":
                'from eegnetreplication_tpu.serve.service import main\n',
            "scripts/bench.py":
                'def run(spawn_replica_fleet):\n'
                '    serve_args = ["--port", "0", "--buckts", "1,8"]\n'
                '    spawn_replica_fleet("ck", 3, serve_args=serve_args)\n'})
        found = rules_for(spawn_args.check(project, contracts),
                          "scripts/bench.py")
        assert ("spawn-arg-unknown", "--buckts") in found
        assert ("spawn-arg-unknown", "--port") not in found

    def test_dict_comprehension_per_replica_args_checked(self, tmp_path):
        # The real fleet builds per_replica_args as a dict comprehension
        # assigned to a name; its literal flags must still be checked.
        service = SERVICE_SRC + CHILD_SRC
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/serve/service.py": service,
            "eegnetreplication_tpu/serve/__main__.py":
                'from eegnetreplication_tpu.serve.service import main\n',
            "scripts/bench.py":
                'def run(spawn_replica_fleet, n, resume):\n'
                '    per_replica_args = {\n'
                '        f"r{i}": ["--port", str(i)]\n'
                '                 + (["--resume"] if resume else [])\n'
                '        for i in range(n)}\n'
                '    spawn_replica_fleet("ck", n,\n'
                '                        per_replica_args=per_replica_args)\n'
            })
        found = rules_for(spawn_args.check(project, contracts),
                          "scripts/bench.py")
        assert ("spawn-arg-unknown", "--resume") in found
        assert ("spawn-arg-unknown", "--port") not in found


class TestLockDisciplinePass:
    BAD = (
        'import threading\n'
        'class Box:\n'
        '    def __init__(self):\n'
        '        self._lock = threading.Lock()\n'
        '        self.items = []\n'
        '    def _count_locked(self):\n'
        '        return len(self.items)\n'
        '    def bad(self):\n'
        '        return self._count_locked()\n'
        '    def good(self):\n'
        '        with self._lock:\n'
        '            return self._count_locked()\n'
        '    def _sibling_locked(self):\n'
        '        return self._count_locked()\n'
    )

    def test_unguarded_call_caught_guarded_ok(self, tmp_path):
        project, contracts = make_project(
            tmp_path, {"eegnetreplication_tpu/box.py": self.BAD})
        findings = lock_discipline.check(project, contracts)
        lines = [f.line for f in findings
                 if f.file == "eegnetreplication_tpu/box.py"]
        assert lines == [9]  # only bad()'s call site

    def test_cross_object_call_caught(self, tmp_path):
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'def f(box):\n    return box._count_locked()\n'})
        found = rules_for(lock_discipline.check(project, contracts),
                          "eegnetreplication_tpu/mod.py")
        assert ("lock-discipline", "_count_locked") in found

    def test_inherited_lock_not_false_positived(self, tmp_path):
        # A same-file base owns the lock; an imported base may too — in
        # neither case is correctly guarded subclass code a violation.
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'import threading\n'
                'from somewhere import ExternalBase\n'
                'class Base:\n'
                '    def __init__(self):\n'
                '        self._lock = threading.Lock()\n'
                '    def _n_locked(self):\n'
                '        return 0\n'
                'class Child(Base):\n'
                '    def get(self):\n'
                '        with self._lock:\n'
                '            return self._n_locked()\n'
                'class Orphan(ExternalBase):\n'
                '    def get(self):\n'
                '        with self._lock:\n'
                '            return self._n_locked()\n'
                '    def bad(self):\n'
                '        return self._n_locked()\n'})
        findings = [f for f in lock_discipline.check(project, contracts)
                    if f.file == "eegnetreplication_tpu/mod.py"]
        assert [f.line for f in findings] == [17]  # only Orphan.bad()

    def test_annassign_and_dataclass_field_locks_recognized(self, tmp_path):
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'import threading\n'
                'from dataclasses import dataclass, field\n'
                'class A:\n'
                '    def __init__(self):\n'
                '        self._lock: threading.Lock = threading.Lock()\n'
                '    def _n_locked(self):\n'
                '        return 0\n'
                '    def get(self):\n'
                '        with self._lock:\n'
                '            return self._n_locked()\n'
                '@dataclass\n'
                'class B:\n'
                '    _lock: threading.Lock = field(\n'
                '        default_factory=threading.Lock)\n'
                '    def _n_locked(self):\n'
                '        return 0\n'
                '    def get(self):\n'
                '        with self._lock:\n'
                '            return self._n_locked()\n'})
        assert not rules_for(lock_discipline.check(project, contracts),
                             "eegnetreplication_tpu/mod.py")

    def test_condition_alias_counts_as_lock(self, tmp_path):
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'import threading\n'
                'class Q:\n'
                '    def __init__(self):\n'
                '        self._cv = threading.Condition()\n'
                '    def _pop_locked(self):\n'
                '        pass\n'
                '    def get(self):\n'
                '        with self._cv:\n'
                '            return self._pop_locked()\n'})
        assert not rules_for(lock_discipline.check(project, contracts),
                             "eegnetreplication_tpu/mod.py")


class TestJitPurityPass:
    def test_decorated_jit_with_clock_caught(self, tmp_path):
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'import time\nimport jax\n'
                '@jax.jit\n'
                'def f(x):\n'
                '    t = time.time()\n'
                '    return x + t\n'})
        found = rules_for(jit_purity.check(project, contracts),
                          "eegnetreplication_tpu/mod.py")
        assert any(r == "jit-impure" for r, _ in found)

    def test_scan_body_logging_and_event_caught(self, tmp_path):
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'from jax import lax\n'
                'from eegnetreplication_tpu.utils.logging import logger\n'
                'def outer(jr, xs):\n'
                '    def body(carry, x):\n'
                '        logger.info("step")\n'
                '        jr.event("epoch", epoch=1)\n'
                '        return carry, x\n'
                '    return lax.scan(body, 0, xs)\n'})
        findings = [f for f in jit_purity.check(project, contracts)
                    if f.file == "eegnetreplication_tpu/mod.py"]
        msgs = " ".join(f.message for f in findings)
        assert "logging call" in msgs and "journal .event" in msgs

    def test_one_level_callee_impurity_caught(self, tmp_path):
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'import random\nimport jax\n'
                'def helper(x):\n'
                '    return x * random.random()\n'
                '@jax.jit\n'
                'def f(x):\n'
                '    return helper(x)\n'})
        found = rules_for(jit_purity.check(project, contracts),
                          "eegnetreplication_tpu/mod.py")
        assert any(r == "jit-impure" for r, _ in found)

    def test_pure_jit_and_unjitted_side_effects_ok(self, tmp_path):
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'import time\nimport jax\nimport jax.numpy as jnp\n'
                '@jax.jit\n'
                'def f(x):\n'
                '    return jnp.tanh(x)\n'
                'def dispatcher(x):\n'
                '    t0 = time.perf_counter()\n'
                '    y = f(x)\n'
                '    return y, time.perf_counter() - t0\n'})
        assert not rules_for(jit_purity.check(project, contracts),
                             "eegnetreplication_tpu/mod.py")

    def test_bare_name_and_module_alias_imports_caught(self, tmp_path):
        # `from time import perf_counter` / `import time as t` /
        # `import numpy as np` must not smuggle impurity past the pass.
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'import jax\nimport time as t\nimport numpy as np\n'
                'from time import perf_counter\n'
                'from random import random as rnd\n'
                '@jax.jit\n'
                'def f(x):\n'
                '    return x + perf_counter()\n'
                '@jax.jit\n'
                'def g(x):\n'
                '    return x + t.time()\n'
                '@jax.jit\n'
                'def h(x):\n'
                '    return x + np.random.rand() + rnd()\n'})
        findings = [f for f in jit_purity.check(project, contracts)
                    if f.file == "eegnetreplication_tpu/mod.py"]
        msgs = " ".join(f.message for f in findings)
        assert "time.perf_counter" in msgs
        assert "time.time" in msgs
        assert "RNG" in msgs
        assert len(findings) >= 4

    def test_jax_random_is_pure(self, tmp_path):
        # `from jax import random` must canonicalize to jax.random (on-
        # device RNG, pure), not be mistaken for stdlib random.
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'import jax\nfrom jax import random\n'
                '@jax.jit\n'
                'def f(key, x):\n'
                '    return x + random.uniform(key, x.shape)\n'})
        assert not rules_for(jit_purity.check(project, contracts),
                             "eegnetreplication_tpu/mod.py")

    def test_vmap_var_resolution_one_hop(self, tmp_path):
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'import time\nimport jax\n'
                'def run_one(x):\n'
                '    return x + time.time()\n'
                'def build():\n'
                '    vmapped = jax.vmap(run_one)\n'
                '    return jax.jit(vmapped)\n'})
        found = rules_for(jit_purity.check(project, contracts),
                          "eegnetreplication_tpu/mod.py")
        assert any(r == "jit-impure" for r, _ in found)


class TestSingleSourcePass:
    def test_pr10_hand_spelled_header_set_caught(self, tmp_path):
        # The PR-10 regression: a hand-spelled forwarding set that
        # silently dropped X-Model.
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/serve/fleet/front.py":
                'def forward(headers):\n'
                '    keep = ("X-Deadline-Ms", "X-Priority")\n'
                '    return {h: headers[h] for h in keep if h in headers}\n'})
        found = rules_for(single_source.check(project, contracts),
                          "eegnetreplication_tpu/serve/fleet/front.py")
        assert any(r == "header-set-hand-spelled" for r, _ in found)

    def test_hand_spelled_header_dict_caught(self, tmp_path):
        # Dict-literal spelling (the natural HTTP-forwarding shape) is
        # the same drift bug through its keys.
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/serve/fleet/front.py":
                'def forward(d, p):\n'
                '    return {"X-Deadline-Ms": d, "X-Priority": p}\n'})
        found = rules_for(single_source.check(project, contracts),
                          "eegnetreplication_tpu/serve/fleet/front.py")
        assert any(r == "header-set-hand-spelled" for r, _ in found)

    def test_typod_site_param_default_flagged(self, tmp_path):
        # A probe wrapper (its body fires the param) whose site= default
        # is a typo is a dead probe: flagged, not silently dropped.
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'from eegnetreplication_tpu.resil.inject import fire\n'
                'def probe_all():\n'
                '    fire("good.site")\n'
                '    fire("other.site")\n'
                'def wrap(fn, site="good.sit"):\n'
                '    fire(site)\n    return fn\n'})
        found = rules_for(inject_sites.check(project, contracts))
        assert ("inject-site-unknown", "good.sit") in found

    def test_single_header_and_imported_set_ok(self, tmp_path):
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/serve/fleet/front.py":
                'from eegnetreplication_tpu.serve.service import '
                'PASSTHROUGH_HEADERS\n'
                'def forward(headers):\n'
                '    model = headers.get("X-Model")\n'
                '    return {h: headers[h] for h in PASSTHROUGH_HEADERS\n'
                '            if h in headers}\n'})
        assert not rules_for(single_source.check(project, contracts),
                             "eegnetreplication_tpu/serve/fleet/front.py")


class TestBaselineAndCli:
    def test_baseline_grandfathers_and_stale_fails(self, tmp_path):
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                'def f(jr):\n    jr.event("odd_one")\n'})
        findings = journal_events.check(project, contracts)
        baseline = {
            "journal-event-unknown:eegnetreplication_tpu/mod.py:odd_one":
                {"rule": "journal-event-unknown",
                 "file": "eegnetreplication_tpu/mod.py",
                 "symbol": "odd_one", "why": "fixture"},
            "journal-event-unknown:eegnetreplication_tpu/mod.py:gone":
                {"rule": "journal-event-unknown",
                 "file": "eegnetreplication_tpu/mod.py",
                 "symbol": "gone", "why": "fixture"},
        }
        new, matched, stale = apply_baseline(findings, baseline)
        assert [f.symbol for f in matched] == ["odd_one"]
        assert [e["symbol"] for e in stale] == ["gone"]
        assert all(f.symbol != "odd_one" for f in new)

    # Emits/probes everything the skeleton declares, so a full-CLI run
    # sees exactly one finding: the bad odd_one emission.
    CLEAN_MOD = (
        'from eegnetreplication_tpu.resil.inject import fire\n'
        'def f(jr):\n'
        '    fire("good.site")\n'
        '    fire("other.site")\n'
        '    jr.event("thing_done", a=1, b=2)\n'
        '    jr.event("ghost_event", x=1)\n'
    )

    def test_cli_exit_codes_and_outputs(self, tmp_path, capsys):
        make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py":
                self.CLEAN_MOD + 'def g(jr):\n    jr.event("odd_one")\n'})
        rc = cli.main(["--root", str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "journal-event-unknown" in out
        # Baseline the finding: clean exit; then strip the code, the
        # baseline entry goes stale and the gate fails again.
        bl = tmp_path / "lint_baseline.json"
        bl.write_text(json.dumps({"findings": [
            {"rule": "journal-event-unknown",
             "file": "eegnetreplication_tpu/mod.py",
             "symbol": "odd_one", "why": "fixture"}]}))
        capsys.readouterr()
        assert cli.main(["--root", str(tmp_path)]) == 0
        # Fix the emission (drop odd_one): the baseline entry goes stale
        # and the gate fails until it is deleted.
        (tmp_path / "eegnetreplication_tpu/mod.py").write_text(
            self.CLEAN_MOD)
        capsys.readouterr()
        rc = cli.main(["--root", str(tmp_path), "--json"])
        record = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert record["counts"]["stale_baseline"] == 1

    def test_pass_subset_does_not_stale_other_passes_entries(
            self, tmp_path, capsys):
        # A journal-events baseline entry must not read as stale when
        # only spawn-args runs: skipped passes produce no findings to
        # match, which is not the same as the issue being fixed.
        make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py": self.CLEAN_MOD
            + 'def g(jr):\n    jr.event("odd_one")\n'})
        (tmp_path / "lint_baseline.json").write_text(json.dumps({
            "findings": [{"rule": "journal-event-unknown",
                          "file": "eegnetreplication_tpu/mod.py",
                          "symbol": "odd_one", "why": "fixture"}]}))
        rc = cli.main(["--root", str(tmp_path), "--passes", "spawn-args",
                       "--json"])
        record = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert record["counts"]["stale_baseline"] == 0

    def test_parse_error_reported_as_finding(self, tmp_path):
        make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py": "def broken(:\n"})
        findings = run_all(tmp_path)
        assert any(f.rule == "parse-error" for f in findings)

    def test_empty_passes_selection_is_a_usage_error(self, tmp_path,
                                                     capsys):
        make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py": self.CLEAN_MOD})
        with pytest.raises(SystemExit) as exc:
            cli.main(["--root", str(tmp_path), "--passes", " , "])
        assert exc.value.code == 2
        assert "selected no passes" in capsys.readouterr().err

    def test_malformed_baseline_is_a_usage_error(self, tmp_path, capsys):
        make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py": self.CLEAN_MOD})
        bl = tmp_path / "lint_baseline.json"
        bl.write_text(json.dumps({"findings": [{"file": "x", "why": "no "
                                                "rule or symbol"}]}))
        with pytest.raises(SystemExit) as exc:
            cli.main(["--root", str(tmp_path)])
        assert exc.value.code == 2
        assert "needs 'rule' and 'symbol'" in capsys.readouterr().err
        bl.write_text("{not json")
        capsys.readouterr()
        with pytest.raises(SystemExit) as exc:
            cli.main(["--root", str(tmp_path)])
        assert exc.value.code == 2
        assert "not valid JSON" in capsys.readouterr().err
        # A bare top-level array is valid JSON but not a baseline.
        bl.write_text(json.dumps([{"rule": "x", "symbol": "y"}]))
        capsys.readouterr()
        with pytest.raises(SystemExit) as exc:
            cli.main(["--root", str(tmp_path)])
        assert exc.value.code == 2
        assert "'findings' list" in capsys.readouterr().err

    def test_baseline_and_no_baseline_conflict(self, tmp_path, capsys):
        make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py": self.CLEAN_MOD})
        with pytest.raises(SystemExit) as exc:
            cli.main(["--root", str(tmp_path), "--no-baseline",
                      "--baseline", str(tmp_path / "b.json")])
        assert exc.value.code == 2
        assert "not allowed with" in capsys.readouterr().err

    def test_missing_explicit_baseline_is_a_usage_error(self, tmp_path,
                                                        capsys):
        make_project(tmp_path, {
            "eegnetreplication_tpu/mod.py": self.CLEAN_MOD})
        with pytest.raises(SystemExit) as exc:
            cli.main(["--root", str(tmp_path),
                      "--baseline", str(tmp_path / "typo.json")])
        assert exc.value.code == 2
        assert "does not exist" in capsys.readouterr().err

    def test_non_literal_contract_reported_once(self, tmp_path):
        # A refactor that makes EVENT_REQUIRED/SITES non-literal must
        # produce ONE contract-missing finding at the cause, not flood
        # every call site with bogus unknowns.
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/obs/schema.py":
                'EVENT_REQUIRED = dict(thing_done=("a",))\n'
                'def event_summary(events):\n    return {}\n',
            "eegnetreplication_tpu/resil/inject.py":
                '_CORE = ("good.site",)\nSITES = _CORE + ("other.site",)\n',
            "eegnetreplication_tpu/mod.py":
                'def f(jr):\n    jr.event("thing_done", a=1)\n'})
        je = journal_events.check(project, contracts)
        assert [(f.rule, f.symbol) for f in je] \
            == [("contract-missing", "EVENT_REQUIRED")]
        si = inject_sites.check(project, contracts)
        assert [(f.rule, f.symbol) for f in si] \
            == [("contract-missing", "SITES")]

    def test_lost_faultspec_fields_is_loud(self, tmp_path):
        # Plan-option validation dies silently if FaultSpec's annotated
        # fields stop being extractable; that must be one loud finding.
        project, contracts = make_project(tmp_path, {
            "eegnetreplication_tpu/resil/inject.py":
                'SITES = ("good.site",)\n'
                'class FaultSpec:\n'
                '    def __init__(self, site):\n'
                '        self.site = site\n'
                'def fire(site, **ctx):\n    pass\n',
            "eegnetreplication_tpu/mod.py":
                'from eegnetreplication_tpu.resil.inject import fire\n'
                'def f():\n    fire("good.site")\n'})
        found = rules_for(inject_sites.check(project, contracts))
        assert ("contract-missing", "FaultSpec") in found

    def test_default_root_refuses_non_checkout(self, tmp_path, monkeypatch,
                                               capsys):
        # An installed (site-packages) eegtpu-lint must refuse to guess a
        # root rather than scan a tree with no scripts/baseline and exit
        # 1 on spurious findings.
        monkeypatch.setattr(cli, "_default_root", lambda: tmp_path)
        (tmp_path / "eegnetreplication_tpu").mkdir()
        with pytest.raises(SystemExit) as exc:
            cli.main([])
        assert exc.value.code == 2
        assert "pyproject.toml" in capsys.readouterr().err
