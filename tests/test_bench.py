"""Unit tests for the driver-gate benchmark's plumbing (bench.py).

The heavy stages (trainer compiles, torch baseline) are exercised by the
BENCH_SMOKE dress runs; these pin the cheap-but-load-bearing pieces that
decide whether a round's artifact is valid: the replay guard, the probe
retry knob, the compile-cache state string, and the last-on-chip
persistence a CPU-fallback line embeds.
"""

import json
import os
import sys
from pathlib import Path
from unittest import mock

import pytest

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

# bench selects its platform at import; force CPU unconditionally so the
# import can never probe (or hang on) the tunneled accelerator in CI —
# setdefault would be a no-op under an exported EEGTPU_PLATFORM=tpu.
os.environ["EEGTPU_PLATFORM"] = "cpu"
import bench  # noqa: E402


class TestAssertFresh:
    def test_distinct_digests_pass(self):
        bench._assert_fresh([b"a", b"b", b"c"], "reps")

    def test_replayed_digests_raise(self):
        with pytest.raises(RuntimeError, match="replayed identical"):
            bench._assert_fresh([b"a", b"b", b"a"], "reps")


class TestProbeRetries:
    def test_default_is_two(self):
        with mock.patch.dict(os.environ, {}, clear=False) as env:
            env.pop("BENCH_PROBE_RETRIES", None)
            env.pop("BENCH_SMOKE", None)
            assert bench._probe_retries() == 2

    def test_smoke_defaults_to_zero(self):
        with mock.patch.dict(os.environ, {"BENCH_SMOKE": "1"}):
            assert bench._probe_retries() == 0

    def test_env_override_and_garbage(self):
        with mock.patch.dict(os.environ, {"BENCH_PROBE_RETRIES": "5"}):
            assert bench._probe_retries() == 5
        with mock.patch.dict(os.environ, {"BENCH_PROBE_RETRIES": "-3"}):
            assert bench._probe_retries() == 0
        # garbage falls back to the non-smoke default; BENCH_SMOKE must be
        # cleared or the fallback legitimately becomes 0
        with mock.patch.dict(os.environ,
                             {"BENCH_PROBE_RETRIES": "nope"}) as env:
            env.pop("BENCH_SMOKE", None)
            assert bench._probe_retries() == 2


class TestCompileCacheState:
    def test_off_without_cache_dir(self):
        with mock.patch.dict(bench.PROBE_INFO, {"cache_dir": None}):
            assert bench._compile_cache_state() == ("off", None, 0)

    def test_cold_and_warm(self, tmp_path):
        with mock.patch.dict(bench.PROBE_INFO, {"cache_dir": str(tmp_path)}):
            assert bench._compile_cache_state() == ("cold", str(tmp_path), 0)
            (tmp_path / "exe1").write_bytes(b"x")
            (tmp_path / "exe2").write_bytes(b"y")
            state, path, entries = bench._compile_cache_state()
        assert state == "warm:2" and entries == 2

    def test_unreadable_dir_is_off(self, tmp_path):
        gone = tmp_path / "missing"
        with mock.patch.dict(bench.PROBE_INFO, {"cache_dir": str(gone)}):
            assert bench._compile_cache_state() == ("off", None, 0)


class TestLastOnchip:
    def test_roundtrip(self, tmp_path, monkeypatch):
        monkeypatch.setattr(bench, "_ONCHIP_LAST_PATH",
                            str(tmp_path / "last.json"))
        record = {"value": 49.9, "unit": "fold-epochs/s",
                  "vs_baseline": 22.4, "platform": "axon",
                  "compile_s": 65.0, "train_mfu_pct": 0.07}
        bench._write_last_onchip(record)
        read = bench._read_last_onchip()
        assert read["value"] == 49.9 and read["vs_baseline"] == 22.4
        assert "utc" in read

    def test_missing_file_is_none(self, tmp_path, monkeypatch):
        monkeypatch.setattr(bench, "_ONCHIP_LAST_PATH",
                            str(tmp_path / "absent.json"))
        assert bench._read_last_onchip() is None

    def test_corrupt_file_is_none(self, tmp_path, monkeypatch):
        p = tmp_path / "bad.json"
        p.write_text("not json{")
        monkeypatch.setattr(bench, "_ONCHIP_LAST_PATH", str(p))
        assert bench._read_last_onchip() is None

    def test_attach_skips_cpu_and_measured_headlines(self, tmp_path,
                                                     monkeypatch):
        monkeypatch.setattr(bench, "_ONCHIP_LAST_PATH",
                            str(tmp_path / "last.json"))
        bench._write_last_onchip({"value": 49.4, "unit": "fold-epochs/s",
                                  "vs_baseline": 17.1, "platform": "tpu",
                                  "compile_s": 310.0,
                                  "train_mfu_pct": 0.07})
        rec = {"platform": "cpu", "value": 0.0}
        bench._attach_last_onchip(rec)
        assert "last_onchip" not in rec  # cpu lines attach elsewhere
        rec = {"platform": "tpu", "value": 49.4}
        bench._attach_last_onchip(rec)
        assert "last_onchip" not in rec  # headline measured: don't shadow
        rec = {"platform": "tpu", "value": 0.0, "error": "mid-run death"}
        bench._attach_last_onchip(rec)
        assert rec["last_onchip"]["value"] == 49.4


class TestCsScaleSummary:
    def test_reads_ok_record(self, tmp_path, monkeypatch):
        # Value assertions run against a fixture, not the committed
        # artifact, so future re-measurements (different parameters, or a
        # committed fault log) change a benchmark record without breaking
        # the suite (ADVICE r3).
        rec = tmp_path / "BENCH_CS_SCALE.json"
        rec.write_text(json.dumps({
            "ok": True, "platform": "tpu", "n_folds": 90, "epochs": 500,
            "wall_s": 4532.3, "protocol_fold_epochs_per_s": 14.71,
            "utc": "2026-07-31T03:46:47Z"}))
        monkeypatch.setattr(bench, "_CS_SCALE_PATH", str(rec))
        summary = bench._read_cs_scale_summary()
        assert summary is not None
        assert summary["n_folds"] == 90 and summary["epochs"] == 500
        assert summary["platform"] == "tpu"
        assert summary["protocol_fold_epochs_per_s"] > 0
        # Pre-val-loss-signal record: the summary flags its own freshness.
        assert summary["freshness"] == "record predates val-loss signal"

    def test_fresh_record_carries_val_loss_signal(self, tmp_path,
                                                  monkeypatch):
        rec = tmp_path / "BENCH_CS_SCALE.json"
        rec.write_text(json.dumps({
            "ok": True, "platform": "tpu", "n_folds": 90, "epochs": 500,
            "wall_s": 4000.0, "protocol_fold_epochs_per_s": 15.0,
            "utc": "2026-08-01T00:00:00Z",
            "distinct_fold_val_losses": 90}))
        monkeypatch.setattr(bench, "_CS_SCALE_PATH", str(rec))
        summary = bench._read_cs_scale_summary()
        assert summary["distinct_fold_val_losses"] == 90
        assert "freshness" not in summary

    def test_committed_artifact_parses_if_ok(self):
        # The committed artifact itself: sound types/ranges, never specific
        # parameter values (those may change with future re-measurements).
        summary = bench._read_cs_scale_summary()
        if summary is not None:  # a committed fault log reads as None
            assert summary["platform"] in ("tpu", "cpu")
            assert isinstance(summary["n_folds"], int)
            assert summary["n_folds"] > 0
            assert isinstance(summary["epochs"], int) and summary["epochs"] > 0
            assert summary["wall_s"] > 0
            assert summary["protocol_fold_epochs_per_s"] > 0
            assert isinstance(summary["utc"], str) and summary["utc"]

    def test_not_ok_record_is_none(self, tmp_path, monkeypatch):
        bad = tmp_path / "BENCH_CS_SCALE.json"
        bad.write_text(json.dumps({"ok": False, "error": "device fault"}))
        monkeypatch.setattr(bench, "_CS_SCALE_PATH", str(bad))
        assert bench._read_cs_scale_summary() is None

    def test_missing_record_is_none(self, tmp_path, monkeypatch):
        monkeypatch.setattr(bench, "_CS_SCALE_PATH",
                            str(tmp_path / "absent.json"))
        assert bench._read_cs_scale_summary() is None


class TestLateReprobe:
    """CPU-fallback promotion: re-probe with leftover budget, promote a
    successful child accelerator line to the headline (VERDICT r3 item 1)."""

    def _cpu_record(self):
        return {"metric": "within_subject_training_throughput",
                "value": 0.12, "vs_baseline": 0.07, "platform": "cpu",
                "compile_s": 60.0, "fallback_reason": "probe timed out",
                "probe_attempts": 3, "probe_seconds": 270.0}

    def test_forced_cpu_never_reprobes(self):
        rec = dict(self._cpu_record())
        with mock.patch.dict(bench.PROBE_INFO, {"forced": True}), \
                mock.patch("eegnetreplication_tpu.utils.platform."
                           "probe_accelerator_info") as probe:
            bench._attempt_late_tpu_promotion(rec, 1500.0, __import__(
                "time").perf_counter())
        probe.assert_not_called()
        assert "late_reprobe" not in rec

    def test_no_budget_skips(self):
        import time

        rec = dict(self._cpu_record())
        with mock.patch.dict(bench.PROBE_INFO, {"forced": False}), \
                mock.patch("eegnetreplication_tpu.utils.platform."
                           "probe_accelerator_info") as probe:
            # t_start far in the past: budget exhausted
            bench._attempt_late_tpu_promotion(
                rec, 300.0, time.perf_counter() - 290.0)
        probe.assert_not_called()
        assert rec["late_reprobe"].startswith("skipped:")
        assert rec["platform"] == "cpu" and rec["value"] == 0.12

    def test_probe_still_down_keeps_cpu_line(self):
        import time

        rec = dict(self._cpu_record())
        with mock.patch.dict(bench.PROBE_INFO, {"forced": False}), \
                mock.patch("eegnetreplication_tpu.utils.platform."
                           "probe_accelerator_info",
                           return_value={"result": None,
                                         "reason": "probe timed out"}):
            bench._attempt_late_tpu_promotion(rec, 1500.0,
                                              time.perf_counter())
        assert rec["late_reprobe"]["probe_result"] is None
        assert rec["platform"] == "cpu" and rec["value"] == 0.12

    def test_success_promotes_child_line(self):
        import time

        rec = dict(self._cpu_record())
        child_line = json.dumps({
            "metric": "within_subject_training_throughput", "value": 49.4,
            "vs_baseline": 17.1, "platform": "tpu", "compile_s": 12.0})
        done = mock.Mock(stdout="noise\n" + child_line + "\n", stderr="")
        with mock.patch.dict(bench.PROBE_INFO, {"forced": False}), \
                mock.patch("eegnetreplication_tpu.utils.platform."
                           "probe_accelerator_info",
                           return_value={"result": "tpu",
                                         "reason": "ok"}), \
                mock.patch.object(bench.subprocess, "run",
                                  return_value=done) as run:
            bench._attempt_late_tpu_promotion(rec, 1500.0,
                                              time.perf_counter())
        assert rec["platform"] == "tpu" and rec["value"] == 49.4
        assert rec["late_reprobe"]["promoted"] is True
        assert rec["first_attempt_cpu"]["value"] == 0.12
        env = run.call_args.kwargs["env"]
        assert env["EEGTPU_PLATFORM"] == "tpu"
        assert env["BENCH_LATE_REPROBE"] == "0"  # no recursion

    def test_child_error_keeps_cpu_line(self):
        import time

        rec = dict(self._cpu_record())
        child_line = json.dumps({"value": 0.0, "platform": "tpu",
                                 "error": "watchdog: exceeded"})
        done = mock.Mock(stdout=child_line + "\n", stderr="")
        with mock.patch.dict(bench.PROBE_INFO, {"forced": False}), \
                mock.patch("eegnetreplication_tpu.utils.platform."
                           "probe_accelerator_info",
                           return_value={"result": "tpu",
                                         "reason": "ok"}), \
                mock.patch.object(bench.subprocess, "run",
                                  return_value=done):
            bench._attempt_late_tpu_promotion(rec, 1500.0,
                                              time.perf_counter())
        assert rec["platform"] == "cpu" and rec["value"] == 0.12
        assert rec["late_reprobe"]["promoted"] is False
        assert "watchdog" in rec["late_reprobe"]["child_error"]


class TestFlopsFields:
    def test_fields_derive_from_rates(self):
        counts = {"fold_epoch_flops": 2.864e9,
                  "eval_forward_flops_pool": 1.86e9}
        record = {"value": 100.0, "fold36_epochs_per_s": 50.0,
                  "eval_fused_trials_per_s": 8000}
        with mock.patch.object(bench, "_flops_accounting",
                               lambda timeout_s=0: counts):
            bench._add_flops_fields(record)
        assert record["fold_epoch_gflops"] == 2.864
        assert record["train_gflops_per_s"] == pytest.approx(286.4)
        assert record["fold36_gflops_per_s"] == pytest.approx(143.2)
        # eval rate is per trial: 8000 * (1.86e9 / 576 trials)
        assert record["eval_fused_gflops_per_s"] == pytest.approx(
            8000 * 1.86e9 / bench.N_POOL / 1e9, abs=0.1)
        # CPU platform: FLOP/s only, no MFU fields
        assert not any(k.endswith("_mfu_pct") for k in record)

    def test_unavailable_counts_marked(self):
        record = {"value": 1.0}
        with mock.patch.object(bench, "_flops_accounting",
                               lambda timeout_s=0: {}):
            bench._add_flops_fields(record)
        assert record["flops_error"] == "cost analysis unavailable"


class TestJsonLineContract:
    def test_main_emits_exactly_one_valid_line(self, capsys):
        """Drive the REAL main() with the heavy stages mocked: exactly one
        JSON line on stdout carrying the driver-contract keys plus the
        round-3 diagnostics, and no error field."""
        with mock.patch.object(bench, "bench_tpu",
                               lambda x, y, f: (12.5, 3.0)), \
             mock.patch.object(bench, "bench_torch_reference_style",
                               lambda x, y, f: 2.5), \
             mock.patch.object(bench, "bench_eval_kernels",
                               lambda: {"eval_fused_trials_per_s": 7000}), \
             mock.patch.object(bench, "bench_fold_scale",
                               lambda **k: {"fold36_epochs_per_s": 9.0}), \
             mock.patch.object(bench, "bench_precision_modes",
                               lambda x, y, f: {}), \
             mock.patch.object(bench, "_add_flops_fields",
                               lambda record, **k: None):
            bench.main()
        lines = capsys.readouterr().out.strip().splitlines()
        assert len(lines) == 1, lines
        rec = json.loads(lines[0])
        assert rec["value"] == 12.5
        assert rec["vs_baseline"] == pytest.approx(5.0)
        assert rec["compile_s"] == 3.0
        assert {"metric", "value", "unit", "vs_baseline", "platform",
                "probe_result", "probe_attempts",
                "compile_cache"} <= set(rec)
        assert "error" not in rec
