"""Tests for the visualization layer and the GUI's non-widget helpers."""

import json
import os
import tempfile
import unittest
from pathlib import Path

import matplotlib

matplotlib.use("Agg")  # headless

import numpy as np

from eegnetreplication_tpu.config import EEG_CHANNEL_NAMES, Paths
from eegnetreplication_tpu.viz import (
    ELECTRODE_XY,
    PS,
    FilterSet,
    load_model_filters,
    plot_power_spectra_of_temporal_filters,
    plot_spatial_filters,
    plot_temporal_filters,
    plot_topomap,
)


def _demo_checkpoint_files(tmp: Path):
    """Write one native .npz and one reference .pth checkpoint of an EEGNet."""
    import jax
    import jax.numpy as jnp

    from eegnetreplication_tpu.models import EEGNet
    from eegnetreplication_tpu.training.checkpoint import (
        save_checkpoint,
        save_pth,
    )

    model = EEGNet(n_channels=22, n_times=257)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 22, 257)),
                           train=False)
    npz = tmp / "m.npz"
    pth = tmp / "m.pth"
    save_checkpoint(npz, variables["params"], variables["batch_stats"],
                    metadata={"model": "eegnet"})
    save_pth(pth, variables["params"], variables["batch_stats"],
             f2=model.F2, t_prime=model.n_times // 32)
    return npz, pth


class TestFilterLoading(unittest.TestCase):
    def test_load_both_formats_agree(self):
        with tempfile.TemporaryDirectory() as d:
            npz, pth = _demo_checkpoint_files(Path(d))
            f_npz = load_model_filters(npz)
            f_pth = load_model_filters(pth)
        self.assertEqual(f_npz.temporal.shape, (8, 32))
        self.assertEqual(f_npz.spatial.shape, (16, 22))
        np.testing.assert_allclose(f_npz.temporal, f_pth.temporal, atol=1e-6)
        np.testing.assert_allclose(f_npz.spatial, f_pth.spatial, atol=1e-6)

    def test_unknown_format_raises(self):
        with self.assertRaises(ValueError):
            load_model_filters("model.txt")


class TestPlots(unittest.TestCase):
    def setUp(self):
        rng = np.random.RandomState(0)
        self.filters = FilterSet(
            temporal=rng.randn(8, 32).astype(np.float32),
            spatial=rng.randn(16, 22).astype(np.float32))

    def test_temporal_grid(self):
        fig = plot_temporal_filters(self.filters, show=False)
        self.assertEqual(len(fig.axes), 8)

    def test_spatial_topomaps(self):
        fig = plot_spatial_filters(self.filters, show=False)
        self.assertEqual(len(fig.axes), 16)

    def test_power_spectra(self):
        fig = plot_power_spectra_of_temporal_filters(self.filters, show=False)
        self.assertEqual(len(fig.axes), 8)

    def test_save_path(self):
        with tempfile.TemporaryDirectory() as d:
            out = Path(d) / "fig.png"
            plot_temporal_filters(self.filters, show=False, save_path=out)
            self.assertTrue(out.exists())

    def test_topomap_single_axis(self):
        import matplotlib.pyplot as plt

        fig, ax = plt.subplots()
        plot_topomap(np.arange(22, dtype=float), ax)
        self.assertFalse(ax.axison)
        plt.close(fig)

    def test_electrode_table_covers_all_channels(self):
        self.assertEqual(set(ELECTRODE_XY), set(EEG_CHANNEL_NAMES))


class TestPS(unittest.TestCase):
    def test_parseval_like_scaling(self):
        # A pure tone of amplitude A has single-sided power A^2/2 split into
        # one bin under the 'ps' scaling (2/N^2 * |X|^2 with |X| = A*N/2).
        n, fs = 128, 128.0
        t = np.arange(n) / fs
        x = 3.0 * np.sin(2 * np.pi * 16 * t)
        f, ps = PS(x, fs, method="ps")
        peak = ps[np.argmin(np.abs(f - 16))]
        self.assertAlmostEqual(peak, 9.0 / 2, delta=0.01)

    def test_psd_scaling_differs(self):
        x = np.sin(np.arange(64))
        _, ps = PS(x, 128.0, method="ps")
        _, psd = PS(x, 128.0, method="psd")
        self.assertFalse(np.allclose(ps, psd))


class TestUIHelpers(unittest.TestCase):
    def test_get_report_reads_latest(self):
        from eegnetreplication_tpu.ui import get_report

        with tempfile.TemporaryDirectory() as d:
            paths = Paths.from_root(Path(d))
            paths.reports.mkdir(parents=True)
            payload = {"overall_results": {"average_test_accuracy": 70.0}}
            (paths.reports / "latest_within_subject_report.json").write_text(
                json.dumps(payload))
            reports = get_report(paths)
        self.assertIn("within_subject", reports)
        self.assertNotIn("cross_subject", reports)
        self.assertEqual(
            reports["within_subject"]["overall_results"]
            ["average_test_accuracy"], 70.0)

    def test_get_model_path_prefers_native(self):
        from eegnetreplication_tpu.ui import get_model_path

        with tempfile.TemporaryDirectory() as d:
            paths = Paths.from_root(Path(d))
            paths.models.mkdir(parents=True)
            pth = paths.models / "subject_01_best_model.pth"
            npz = paths.models / "subject_01_best_model.npz"
            pth.touch()
            self.assertEqual(get_model_path("Within-Subject", "01", paths), pth)
            npz.touch()
            self.assertEqual(get_model_path("Within-Subject", "01", paths), npz)
            self.assertEqual(
                get_model_path("Cross-Subject", "01", paths).name,
                "cross_subject_best_model.pth")


@unittest.skipUnless(os.environ.get("DISPLAY"), "no X display")
class TestAppConstruction(unittest.TestCase):
    def test_app_builds_four_tabs(self):
        from eegnetreplication_tpu.ui import App

        app = App()
        try:
            tabs = [app.notebook.tab(t, "text") for t in app.notebook.tabs()]
            self.assertEqual(tabs, ["Training Pipeline", "Logs",
                                    "Training Reports", "Model Exploration"])
        finally:
            app.destroy()

    def test_train_command_carries_model_and_precision(self):
        """The Training tab's TPU-native dropdowns reach the train CLI."""
        from eegnetreplication_tpu.ui import App

        app = App()
        try:
            captured = {}
            app._launch = (lambda args, *a, **k:
                           captured.setdefault("args", args))
            app.train_model_var.set("shallow_convnet")
            app.precision_var.set("bf16")
            app.train_model()
            args = captured["args"]
            self.assertIn("--model", args)
            self.assertEqual(args[args.index("--model") + 1],
                             "shallow_convnet")
            self.assertIn("--precision", args)
            self.assertEqual(args[args.index("--precision") + 1], "bf16")
        finally:
            app.destroy()


class TestHeadlessUILogic(unittest.TestCase):
    """The GUI's widget-free core, exercised without an X display.

    This image has no Xvfb, so ``TestAppConstruction`` skips headless; the
    command builders, report formatting and chart construction the App
    binds to Tk are module-level functions tested here instead
    (VERDICT r2 item 8)."""

    SAMPLE = {
        "overall_results": {"average_test_accuracy": 70.0,
                            "standard_error": 2.5,
                            "best_subject_accuracy": 85.0,
                            "worst_subject_accuracy": 55.0,
                            "accuracy_std": 7.5},
        "per_subject_results": [
            {"subject_id": 1, "test_accuracy": 85.0, "performance_rank": 1},
            {"subject_id": 2, "test_accuracy": 55.0, "performance_rank": 2},
        ],
    }

    def test_train_command_carries_model_and_precision(self):
        from eegnetreplication_tpu.ui import build_train_cmd

        args = build_train_cmd("Within-Subject", 500, True,
                               "shallow_convnet", "bf16")
        self.assertEqual(args[args.index("--model") + 1], "shallow_convnet")
        self.assertEqual(args[args.index("--precision") + 1], "bf16")
        self.assertEqual(args[args.index("--trainingType") + 1],
                         "Within-Subject")
        self.assertEqual(args[args.index("--epochs") + 1], "500")
        self.assertEqual(args[args.index("--generateReport") + 1], "True")

    def test_fetch_dataset_predict_commands(self):
        from eegnetreplication_tpu.ui import (
            build_dataset_cmd,
            build_fetch_cmd,
            build_predict_cmd,
        )

        self.assertEqual(build_fetch_cmd("kaggle")[-2:], ["--src", "kaggle"])
        self.assertIn(".dataset", build_dataset_cmd("moabb")[2])
        predict = build_predict_cmd("/tmp/m.pth", 3)
        self.assertEqual(predict[predict.index("--subject") + 1], "3")
        self.assertEqual(predict[predict.index("--mode") + 1], "Eval")

    def test_report_overview_lines(self):
        from eegnetreplication_tpu.ui import report_overview_lines

        lines = report_overview_lines(self.SAMPLE)
        self.assertEqual(lines[0], "Average Test Accuracy: 70.0%")
        self.assertIn("Standard Error: ±2.5%", lines)
        self.assertIn("Standard Deviation: 7.5%", lines)
        # WS reports carry no standard_error: the line must disappear.
        no_se = {"overall_results": dict(self.SAMPLE["overall_results"])}
        del no_se["overall_results"]["standard_error"]
        self.assertEqual(len(report_overview_lines(no_se)), 4)

    def test_report_table_rows(self):
        from eegnetreplication_tpu.ui import report_table_rows

        rows = report_table_rows(self.SAMPLE, "subject_id")
        self.assertEqual(rows[0], ("Subject 1", "85.0%", 1))
        self.assertEqual(rows[1], ("Subject 2", "55.0%", 2))

    def test_accuracy_chart_figure(self):
        import matplotlib

        matplotlib.use("Agg", force=True)
        from eegnetreplication_tpu.ui import accuracy_chart_figure

        fig = accuracy_chart_figure(self.SAMPLE["per_subject_results"],
                                    "Within-Subject", "subject_id")
        ax = fig.axes[0]
        heights = sorted(p.get_height() for p in ax.patches)
        self.assertEqual(heights, [55.0, 85.0])
        self.assertEqual(ax.get_title(),
                         "Within-Subject - Test Accuracy by Subject")
        # the average line sits at the mean
        avg_lines = [ln for ln in ax.lines
                     if ln.get_linestyle() == "--"]
        self.assertEqual(avg_lines[0].get_ydata()[0], 70.0)


class TestHeadlessUIOnRealReport(unittest.TestCase):
    """The headless formatters against a REAL protocol-generated report
    (not a hand-built sample): the report schema and the GUI's rendering
    layer must agree about keys end to end."""

    def test_generated_ws_report_renders(self):
        from synthetic import make_loader

        from eegnetreplication_tpu.config import DEFAULT_TRAINING, Paths
        from eegnetreplication_tpu.training.protocols import (
            within_subject_training,
        )
        from eegnetreplication_tpu.training.report import generate_ws_report
        from eegnetreplication_tpu.ui import (
            accuracy_chart_figure,
            get_report,
            report_overview_lines,
            report_table_rows,
        )

        with tempfile.TemporaryDirectory() as td:
            paths = Paths.from_root(Path(td))
            loader = make_loader(n_trials=24, n_channels=4, n_times=64)
            result = within_subject_training(
                epochs=2, config=DEFAULT_TRAINING.replace(batch_size=16),
                loader=loader, subjects=(1, 2), paths=paths, seed=0,
                save_models=False)
            generate_ws_report(result.per_subject_test_acc,
                               result.avg_test_acc, result.best_states,
                               epochs=2,
                               config=DEFAULT_TRAINING.replace(batch_size=16),
                               paths=paths)
            report = get_report(paths)["within_subject"]
            lines = report_overview_lines(report)
            self.assertTrue(lines[0].startswith("Average Test Accuracy: "))
            rows = report_table_rows(report, "subject_id")
            self.assertEqual(len(rows), 2)
            for row in rows:  # accuracies render as parseable percentages
                acc = float(row[1].rstrip("%"))
                self.assertTrue(0.0 <= acc <= 100.0, row)
            fig = accuracy_chart_figure(report["per_subject_results"],
                                        "Within-Subject", "subject_id")
            self.assertEqual(len(fig.axes[0].patches), 2)


class TestModelNameSync(unittest.TestCase):
    def test_ui_model_names_match_registry(self):
        """ui.MODEL_NAMES is a names-only copy (the GUI must not import
        flax/jax); it must track the real registry."""
        from eegnetreplication_tpu.models.registry import MODEL_REGISTRY
        from eegnetreplication_tpu.ui import MODEL_NAMES

        self.assertEqual(MODEL_NAMES, sorted(MODEL_REGISTRY))

    def test_performance_overview_lines(self):
        """The Performance tab's headless core: renders whatever artifacts
        exist, skips the rest, degrades to a hint when none do."""
        import json
        import tempfile
        from pathlib import Path

        from eegnetreplication_tpu.ui import performance_overview_lines

        with tempfile.TemporaryDirectory() as d:
            root = Path(d)
            self.assertIn("No benchmark artifacts",
                          performance_overview_lines(root)[0])
            (root / "BENCH_ONCHIP_LAST.json").write_text(json.dumps(
                {"value": 49.4, "vs_baseline": 17.1, "platform": "tpu",
                 "utc": "2026-07-31T03:31:50Z"}))
            (root / "BENCH_CONV_AB.json").write_text(json.dumps(
                {"ok": True, "platform": "cpu", "speedup": 8.94,
                 "banded": {"fold_epochs_per_s": 1.52},
                 "lax": {"fold_epochs_per_s": 0.17}}))
            (root / "BENCH_CS_SCALE.json").write_text("{corrupt")
            lines = performance_overview_lines(root)
        self.assertEqual(len(lines), 2)
        self.assertTrue(any("49.4 fold-epochs/s" in ln for ln in lines))
        self.assertTrue(any("8.94x" in ln for ln in lines))

    def test_performance_lines_on_repo_root(self):
        """Against the real repo root: never raises, always one line+."""
        from eegnetreplication_tpu.ui import performance_overview_lines

        self.assertTrue(len(performance_overview_lines()) >= 1)


# Keep last: classes defined below this guard would be invisible to a
# direct ``python tests/test_viz_ui.py`` run (ADVICE r2).
if __name__ == "__main__":
    unittest.main()
