"""FLOP accounting sanity (utils/flops.py, VERDICT r2 item 3).

The reference measures nothing hardware-relative; these tests pin the cost
model's invariants rather than exact flop numbers (which may shift with
XLA's HLO cost model version): positivity, monotonicity in batch and slot
count, and the MFU denominator table.
"""

import os

import pytest

from eegnetreplication_tpu.models import EEGNet
from eegnetreplication_tpu.training import make_optimizer
from eegnetreplication_tpu.utils.flops import (
    assumed_peak_flops,
    eval_forward_flops,
    eval_step_flops,
    fold_epoch_flops,
    mfu,
    train_step_flops,
)

C, T = 8, 64
MODEL = EEGNet(n_channels=C, n_times=T, F1=4, D=2)


def test_train_step_flops_positive_and_scales_with_batch():
    tx = make_optimizer()
    f16 = train_step_flops(MODEL, tx, 16, (C, T))
    f32 = train_step_flops(MODEL, tx, 32, (C, T))
    assert f16 and f16 > 0
    # doubling the batch roughly doubles the conv flops (sub-linear parts:
    # the optimizer update is batch-independent)
    assert 1.5 < f32 / f16 < 2.5


def test_banded_schedule_counted_at_canonical_cost():
    """MFU honesty: the banded op schedule inflates conv MACs ~8x by
    design; FLOP counts must measure the algorithm (lax schedule) so the
    same model costs the same regardless of conv_impl."""
    tx = make_optimizer()
    lax_f = train_step_flops(MODEL, tx, 16, (C, T))
    banded_f = train_step_flops(
        EEGNet(n_channels=C, n_times=T, F1=4, D=2, conv_impl="banded"),
        tx, 16, (C, T))
    assert banded_f == lax_f


def test_eval_cheaper_than_train():
    tx = make_optimizer()
    assert (eval_step_flops(MODEL, tx, 16, (C, T))
            < train_step_flops(MODEL, tx, 16, (C, T)))


def test_fold_epoch_counts_slots():
    tx = make_optimizer()
    # 33 train samples at batch 16 -> 3 slots; 63 -> 4 slots
    small = fold_epoch_flops(MODEL, tx, batch_size=16, train_pad=33,
                             val_pad=10, sample_shape=(C, T))
    large = fold_epoch_flops(MODEL, tx, batch_size=16, train_pad=63,
                             val_pad=10, sample_shape=(C, T))
    assert small and large and large > small


def test_eval_forward_flops_positive():
    assert eval_forward_flops(MODEL, 64, (C, T)) > 0


def test_peak_table_and_override():
    peak, label = assumed_peak_flops("TPU v5 lite")
    assert peak == 197e12 and "v5e" in label
    peak, _ = assumed_peak_flops("TPU v4")
    assert peak == 275e12
    peak, _ = assumed_peak_flops(None)  # default assumption
    assert peak == 197e12
    os.environ["EEGTPU_PEAK_FLOPS"] = "1e12"
    try:
        peak, label = assumed_peak_flops("TPU v4")
        assert peak == 1e12 and "EEGTPU_PEAK_FLOPS" in label
        assert mfu(5e11, "TPU v4") == pytest.approx(0.5)
    finally:
        del os.environ["EEGTPU_PEAK_FLOPS"]
