"""Unit tests of the multi-seed equivalence combiner's statistics.

The combiner (`scripts/equiv_combine.py`) produces the round-5 equivalence
verdicts; its sign test, Welch CI, and guard rails are load-bearing for
`EQUIV_WS_MULTISEED.json` and are pinned here on constructed records.
"""

import json
import sys
from pathlib import Path

import pytest

SCRIPTS = Path(__file__).resolve().parents[1] / "scripts"
sys.path.insert(0, str(SCRIPTS))

import equiv_combine  # noqa: E402


def _write_records(tmp_path, arm, accs_by_seed, epochs=100):
    """accs_by_seed: list (one per seed) of dicts subject -> acc."""
    for i, accs in enumerate(accs_by_seed):
        rec = {"epochs": epochs,
               "per_subject": {str(s): {"test_acc": a}
                               for s, a in accs.items()}}
        (tmp_path / f"{arm}_{i}.json").write_text(json.dumps(rec))
    return str(tmp_path / f"{arm}_*.json")


class TestSignTest:
    def test_exact_binomial_values(self):
        # 7-of-7 one-signed: classic p = 2 * (1/2)^7
        assert equiv_combine._binom_two_sided_p(7, 7) == pytest.approx(
            2 * 0.5 ** 7)
        # balanced: p caps at 1
        assert equiv_combine._binom_two_sided_p(4, 9) == pytest.approx(
            1.0, abs=0.35)
        assert equiv_combine._binom_two_sided_p(0, 0) == 1.0

    def test_ties_drop_out(self, tmp_path, capsys):
        """Exact-zero deltas are ties: 4 negative + 2 zero must be tested
        as 4-of-4, not 4-of-6 (review r5)."""
        base = {s: 60.0 for s in range(1, 7)}
        shifted = {**base, **{s: 62.0 for s in (1, 2, 3, 4)}}
        fw = _write_records(tmp_path, "fw", [base] * 3)
        th = _write_records(tmp_path, "th", [shifted] * 3)
        out = tmp_path / "out.json"
        equiv_combine.main(["--framework", fw, "--torch", th,
                            "--out", str(out)])
        rec = json.loads(out.read_text())
        assert rec["subjects_delta_zero"] == 2
        assert rec["subjects_delta_negative"] == 4
        assert rec["sign_test_p"] == pytest.approx(2 * 0.5 ** 4)


class TestVerdicts:
    def test_tost_needs_containment_not_overlap(self, tmp_path):
        """A noisy arm whose CI straddles far past +-1 pp must NOT claim
        equivalent_1pp (review r5: overlap rewards noise)."""
        import numpy as np

        rng = np.random.RandomState(0)
        fw_seeds = [{1: 60.0 + 8 * rng.randn()} for _ in range(3)]
        th_seeds = [{1: 60.0 + 8 * rng.randn()} for _ in range(3)]
        fw = _write_records(tmp_path, "fw", fw_seeds)
        th = _write_records(tmp_path, "th", th_seeds)
        out = tmp_path / "out.json"
        equiv_combine.main(["--framework", fw, "--torch", th,
                            "--out", str(out)])
        rec = json.loads(out.read_text())
        ci = rec["per_subject"]["1"]["delta_ci95"]
        assert ci[1] - ci[0] > 2.0  # wide CI by construction
        assert rec["equivalent_1pp"] is False
        assert rec["consistent_with_1pp"] is True

    def test_identical_arms_degenerate_flagged(self, tmp_path):
        same = [{1: 70.0, 2: 55.0}] * 3
        fw = _write_records(tmp_path, "fw", same)
        th = _write_records(tmp_path, "th", same)
        out = tmp_path / "out.json"
        equiv_combine.main(["--framework", fw, "--torch", th,
                            "--out", str(out)])
        rec = json.loads(out.read_text())
        assert all(v["degenerate_variance"]
                   for v in rec["per_subject"].values())
        assert rec["subjects_delta_zero"] == 2


class TestGuards:
    def test_min_seeds_enforced(self, tmp_path):
        fw = _write_records(tmp_path, "fw", [{1: 60.0}] * 2)
        th = _write_records(tmp_path, "th", [{1: 60.0}] * 3)
        with pytest.raises(SystemExit, match="multi-seed design"):
            equiv_combine.main(["--framework", fw, "--torch", th,
                                "--out", str(tmp_path / "o.json")])

    def test_cross_arm_epoch_mismatch_rejected(self, tmp_path):
        fw = _write_records(tmp_path, "fw", [{1: 60.0}] * 3, epochs=200)
        th = _write_records(tmp_path, "th", [{1: 60.0}] * 3, epochs=100)
        with pytest.raises(SystemExit, match="arms trained differently"):
            equiv_combine.main(["--framework", fw, "--torch", th,
                                "--out", str(tmp_path / "o.json")])

    def test_missing_subject_rejected(self, tmp_path):
        fw = _write_records(tmp_path, "fw", [{1: 60.0, 2: 50.0}] * 3)
        th = _write_records(tmp_path, "th",
                            [{1: 60.0, 2: 50.0}, {1: 60.0, 2: 50.0},
                             {1: 60.0}])
        with pytest.raises(SystemExit, match="missing subjects"):
            equiv_combine.main(["--framework", fw, "--torch", th,
                                "--out", str(tmp_path / "o.json")])
