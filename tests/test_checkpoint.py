"""Checkpoint tests: native npz roundtrip + reference .pth interop.

The .pth interop test is the strong one: exported state_dicts must produce
identical logits when loaded into an independent torch EEGNet, and a torch
state_dict must roundtrip back into flax bit-exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from eegnetreplication_tpu.models import EEGNet
from eegnetreplication_tpu.training import checkpoint as ckpt


@pytest.fixture
def eegnet_vars():
    model = EEGNet()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 22, 257)),
                           train=False)
    return model, variables


class TestNativeFormat:
    def test_roundtrip(self, tmp_path, eegnet_vars):
        model, variables = eegnet_vars
        meta = {"model": "eegnet", "n_times": 257}
        p = ckpt.save_checkpoint(tmp_path / "ck.npz", variables["params"],
                                 variables["batch_stats"], meta)
        params, batch_stats, metadata = ckpt.load_checkpoint(p)
        assert metadata == meta
        for (ka, a), (kb, b) in zip(
                jax.tree_util.tree_leaves_with_path(variables["params"]),
                jax.tree_util.tree_leaves_with_path(params)):
            np.testing.assert_array_equal(np.asarray(a), b)
        restored = {"params": params, "batch_stats": batch_stats}
        x = jnp.asarray(np.random.RandomState(0).randn(2, 22, 257), jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(model.apply(variables, x, train=False)),
            np.asarray(model.apply(restored, x, train=False)))

    def test_metadata_records_T(self, tmp_path, eegnet_vars):
        _, variables = eegnet_vars
        p = ckpt.save_checkpoint(tmp_path / "ck.npz", variables["params"],
                                 variables["batch_stats"],
                                 {"n_times": 257})
        _, _, meta = ckpt.load_checkpoint(p)
        assert meta["n_times"] == 257  # quirk Q4 fixed: T is explicit


class TestTorchInterop:
    def test_state_dict_keys_match_reference_naming(self, eegnet_vars):
        _, variables = eegnet_vars
        sd = ckpt.to_torch_state_dict(variables["params"],
                                      variables["batch_stats"], 16, 8)
        # the exact keys the reference GUI reads (ui.py:518, ui.py:548)
        assert "temporal.0.weight" in sd
        assert "spatial.weight" in sd
        assert sd["temporal.0.weight"].shape == (8, 1, 1, 32)
        assert sd["spatial.weight"].shape == (16, 1, 22, 1)
        assert sd["classifier.weight"].shape == (4, 128)

    def test_flax_torch_flax_roundtrip_bitexact(self, eegnet_vars):
        _, variables = eegnet_vars
        sd = ckpt.to_torch_state_dict(variables["params"],
                                      variables["batch_stats"], 16, 8)
        params, batch_stats = ckpt.from_torch_state_dict(sd, 16, 8)
        for (pa, a), (pb, b) in zip(
                jax.tree_util.tree_leaves_with_path(variables["params"]),
                jax.tree_util.tree_leaves_with_path(params)):
            np.testing.assert_array_equal(np.asarray(a), b, err_msg=str(pa))

    def test_pth_loads_into_torch_model_with_same_logits(self, tmp_path,
                                                         eegnet_vars):
        torch = pytest.importorskip("torch")
        from test_parity_torch import build_torch_eegnet

        model, variables = eegnet_vars
        p = ckpt.save_pth(tmp_path / "m.pth", variables["params"],
                          variables["batch_stats"], 16, 8)
        tmodel = build_torch_eegnet()
        tmodel.load_state_dict(torch.load(p, map_location="cpu"))
        tmodel.eval()

        x = np.random.RandomState(1).randn(4, 22, 257).astype(np.float32)
        flax_out = np.asarray(model.apply(variables, jnp.asarray(x),
                                          train=False))
        with torch.no_grad():
            torch_out = tmodel(torch.tensor(x)).numpy()
        np.testing.assert_allclose(flax_out, torch_out, rtol=1e-4, atol=1e-5)

    def test_load_pth_back_to_flax(self, tmp_path, eegnet_vars):
        pytest.importorskip("torch")
        model, variables = eegnet_vars
        p = ckpt.save_pth(tmp_path / "m.pth", variables["params"],
                          variables["batch_stats"], 16, 8)
        params, batch_stats = ckpt.load_pth(p, 16, 8)
        x = jnp.asarray(np.random.RandomState(2).randn(2, 22, 257), jnp.float32)
        np.testing.assert_allclose(
            np.asarray(model.apply(variables, x, train=False)),
            np.asarray(model.apply({"params": params,
                                    "batch_stats": batch_stats}, x,
                                   train=False)),
            rtol=1e-6)


class TestResumableCheckpoint:
    """Optimizer-state + step persistence (the reference is save-only)."""

    def test_train_state_roundtrip_resumes_identically(self, tmp_path,
                                                       eegnet_vars):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from eegnetreplication_tpu.models import EEGNet
        from eegnetreplication_tpu.training.checkpoint import (
            load_train_state,
            save_checkpoint,
        )
        from eegnetreplication_tpu.training.steps import (
            TrainState,
            make_optimizer,
            train_step,
        )

        model = EEGNet(n_channels=8, n_times=64)
        variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 64)),
                               train=False)
        tx = make_optimizer()
        state = TrainState.create(variables, tx)
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(16, 8, 64), jnp.float32)
        y = jnp.asarray(rng.randint(0, 4, 16), jnp.int32)
        w = jnp.ones(16)

        # A few steps so Adam moments are non-trivial.
        for i in range(3):
            state, _ = train_step(model, tx, state, x, y, w,
                                  jax.random.PRNGKey(i))

        path = tmp_path / "resume.npz"
        save_checkpoint(path, state.params, state.batch_stats,
                        metadata={"model": "eegnet"},
                        opt_state=state.opt_state, step=3)
        restored, step, meta = load_train_state(path, tx)
        assert step == 3
        assert meta["model"] == "eegnet"

        # One more step from each must match exactly (moments restored).
        next_a, loss_a = train_step(model, tx, state, x, y, w,
                                    jax.random.PRNGKey(9))
        next_b, loss_b = train_step(model, tx, restored, x, y, w,
                                    jax.random.PRNGKey(9))
        assert float(loss_a) == float(loss_b)
        for la, lb in zip(jax.tree_util.tree_leaves(next_a.params),
                          jax.tree_util.tree_leaves(next_b.params)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))

    def test_weights_only_checkpoint_gets_fresh_optimizer(self, tmp_path,
                                                          eegnet_vars):
        from eegnetreplication_tpu.training.checkpoint import (
            load_train_state,
            save_checkpoint,
        )
        from eegnetreplication_tpu.training.steps import make_optimizer

        model, variables = eegnet_vars
        params = variables["params"]
        path = tmp_path / "weights_only.npz"
        save_checkpoint(path, params, variables["batch_stats"])
        tx = make_optimizer()
        state, step, _ = load_train_state(path, tx)
        assert step == 0
        import jax

        assert jax.tree_util.tree_structure(state.opt_state) == \
            jax.tree_util.tree_structure(tx.init(params))


class TestProfilingUtils:
    def test_step_timer_rates(self):
        import time

        from eegnetreplication_tpu.utils.profiling import StepTimer

        timer = StepTimer()
        for _ in range(3):
            with timer:
                time.sleep(0.01)
        assert len(timer.times) == 3
        assert timer.total >= 0.03
        assert timer.rate(units_per_step=2.0) > 0

    def test_trace_noop_without_dir(self):
        from eegnetreplication_tpu.utils.profiling import trace

        with trace(None):
            pass  # must not require jax or write anything
