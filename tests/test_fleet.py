"""Fleet serving subsystem (``eegnetreplication_tpu/serve/fleet/``).

Covers the ISSUE-6 acceptance surface: health-gated membership (drain on
degraded/stale, out on unreachable, automatic rejoin), least-loaded
dispatch with per-replica breakers and zero-failure failover off a dead
replica, the rolling canary reload (shadow compare, rollback, corrupt
push leaves the fleet untouched), and the ``serve_bench.py --fleet``
tier-1 selftest (scaling floor + kill-one-replica-under-load).

The membership/router/canary machinery is pure HTTP orchestration, so
most tests run against scriptable stdlib fake replicas — no JAX, no
subprocesses; the end-to-end truth (real engines, real processes, real
SIGKILL) is the selftest leg.
"""

import json
import os
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import pytest

from eegnetreplication_tpu.obs import journal as obs_journal
from eegnetreplication_tpu.obs import schema
from eegnetreplication_tpu.serve.fleet import membership as ms
from eegnetreplication_tpu.serve.fleet.canary import RollingReload
from eegnetreplication_tpu.serve.fleet.router import (
    AllReplicasBusy,
    FleetRouter,
    NoLiveReplicas,
)

REPO = Path(__file__).resolve().parent.parent


class FakeReplica:
    """A scriptable single-replica double: /healthz, /predict, /reload.

    Behavior knobs are plain attributes, mutated mid-test to simulate
    degradation, death (``stop()``), bad pushes, and disagreeing models.
    """

    def __init__(self, digest: str = "d-old", port: int = 0):
        self.digest = digest
        self.healthz_digest = None           # override what /healthz shows
        self.precision = "fp32"              # what /healthz advertises
        self.buckets = (1, 8, 32)            # the replica's active ladder
        self.queue_depth = 0
        self.degraded: list[str] = []        # non-empty -> healthz 503
        self.predict_status = 200
        self.predict_delay = 0.0             # gray knob: slow, not dead
        self.predictions = [0, 1, 2]         # served to every /predict
        # reload_fn(checkpoint) -> (status, digest-or-error)
        self.reload_fn = lambda ck: (200, "d-new")
        self.slo_breached: list[str] = []     # advertised on /healthz
        self.zoo = None                       # zoo advert (dict) or None
        self.log: list[tuple[str, bytes]] = []
        self.headers_log: list[dict] = []     # per-/predict request headers
        fake = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.0: one connection per request.  A stopped fake must
            # look DEAD, like a SIGKILLed replica whose sockets the OS
            # closed — with keep-alive, stdlib handler threads would keep
            # serving pooled connections after shutdown().  The pooled
            # keep-alive path is exercised end-to-end by the selftest leg.
            protocol_version = "HTTP/1.0"

            def log_message(self, *a):  # noqa: A003 — quiet
                pass

            def _reply(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                if self.path == "/healthz":
                    code = 503 if fake.degraded else 200
                    self._reply(code, {
                        "status": "degraded" if fake.degraded else "ok",
                        "degraded": fake.degraded,
                        "variables_digest": (fake.healthz_digest
                                             or fake.digest),
                        "precision": fake.precision,
                        "buckets": list(fake.buckets),
                        "slo": {"breached": list(fake.slo_breached)},
                        "zoo": fake.zoo,
                        "queue_depth_requests": fake.queue_depth,
                        "queue_depth_trials": fake.queue_depth})
                    return
                self._reply(404, {})

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length", 0) or 0)
                body = self.rfile.read(n) if n else b""
                fake.log.append((self.path, body))
                if self.path == "/predict":
                    fake.headers_log.append(dict(self.headers.items()))
                    if fake.predict_delay:
                        time.sleep(fake.predict_delay)
                    if fake.predict_status != 200:
                        self._reply(fake.predict_status,
                                    {"error": "scripted"})
                        return
                    self._reply(200, {"predictions": fake.predictions,
                                      "n": len(fake.predictions),
                                      "model_digest": fake.digest})
                    return
                if self.path == "/reload":
                    ck = json.loads(body.decode()).get("checkpoint")
                    status, result = fake.reload_fn(ck)
                    if status == 200:
                        fake.digest = result
                        self._reply(200, {"status": "ok",
                                          "model_digest": result})
                    else:
                        self._reply(status, {"error": result})
                    return
                self._reply(404, {})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def predict_count(self) -> int:
        return sum(1 for path, _ in self.log if path == "/predict")

    def reload_checkpoints(self) -> list[str]:
        return [json.loads(body.decode()).get("checkpoint")
                for path, body in self.log if path == "/reload"]

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


@pytest.fixture
def journal(tmp_path):
    with obs_journal.run(tmp_path / "obs", config={}) as jr:
        yield jr


def _fleet(fakes, journal, **membership_kw):
    replicas = [ms.Replica(f"r{i}", fake.url, journal=journal)
                for i, fake in enumerate(fakes)]
    membership = ms.FleetMembership(replicas, journal=journal,
                                    **membership_kw)
    router = FleetRouter(membership, journal=journal)
    return replicas, membership, router


def _events(jr, kind):
    return [e for e in schema.read_events(jr.events_path, complete=False)
            if e["event"] == kind]


class TestMembership:
    def test_join_drain_on_degraded_and_recover(self, journal):
        fake = FakeReplica()
        try:
            replicas, membership, _ = _fleet([fake], journal)
            r = replicas[0]
            assert r.state == ms.JOINING
            membership.poll_once()
            assert r.state == ms.LIVE
            assert r.digest == "d-old"
            fake.degraded = ["circuit_open"]
            membership.poll_once()
            assert r.state == ms.DRAINING
            assert membership.dispatchable() == []
            fake.degraded = []
            membership.poll_once()
            assert r.state == ms.LIVE
            transitions = [(e["state"], e["reason"])
                           for e in _events(journal, "fleet_member")]
            assert transitions == [("live", "joined"),
                                   ("draining", "circuit_open"),
                                   ("live", "recovered")]
        finally:
            fake.stop()

    def test_snapshot_mirrors_ladder_and_precision(self, journal):
        """ISSUE-8 acceptance: each replica's /healthz-advertised active
        ladder + serving precision flow into the membership snapshot the
        fleet /healthz endpoint returns."""
        fake = FakeReplica()
        fake.precision = "int8"
        fake.buckets = (1, 4, 8, 64)
        try:
            replicas, membership, _ = _fleet([fake], journal)
            membership.poll_once()
            r = replicas[0]
            assert r.precision == "int8"
            assert r.buckets == (1, 4, 8, 64)
            snap = membership.snapshot()[0]
            assert snap["precision"] == "int8"
            assert snap["buckets"] == [1, 4, 8, 64]
            # A retune shows up at the next poll.
            fake.buckets = (1, 4, 8, 128)
            membership.poll_once()
            assert membership.snapshot()[0]["buckets"] == [1, 4, 8, 128]
        finally:
            fake.stop()

    def test_unreachable_goes_out_and_rejoins(self, journal):
        fake = FakeReplica()
        port = fake.port
        replicas, membership, _ = _fleet([fake], journal,
                                         fail_threshold=2)
        r = replicas[0]
        membership.poll_once()
        assert r.state == ms.LIVE
        fake.stop()
        membership.poll_once()
        assert r.state == ms.LIVE  # one failed poll is not a verdict
        membership.poll_once()
        assert r.state == ms.OUT
        # "Restart" on the same port (allow_reuse_address): the next
        # healthy poll rejoins it with no external intervention.
        fake2 = FakeReplica(port=port)
        try:
            membership.poll_once()
            assert r.state == ms.LIVE
            reasons = [e["reason"]
                       for e in _events(journal, "fleet_member")]
            assert reasons == ["joined", "unreachable: ConnectionRefusedError",
                               "rejoined"]
        finally:
            fake2.stop()

    def test_stale_heartbeat_file_drains_without_flapping(self, journal,
                                                          tmp_path):
        fake = FakeReplica()

        def write_beat(age_s: float):
            hb_file.write_text(json.dumps(
                {"phase": "serve_idle", "beat": 3,
                 "t": time.time() - age_s, "pid": os.getpid()}))

        try:
            hb_file = tmp_path / "hb.json"
            write_beat(0.0)
            replica = ms.Replica("r0", fake.url, heartbeat_file=hb_file,
                                 journal=journal)
            membership = ms.FleetMembership([replica], journal=journal)
            membership.poll_once()
            assert replica.state == ms.LIVE
            write_beat(3600.0)  # the worker wedges: healthz still 200
            membership.poll_once()
            assert replica.state == ms.DRAINING
            # No live<->draining flapping while the beat stays stale: a
            # healthy healthz must not re-admit a wedged worker.
            membership.poll_once()
            membership.poll_once()
            assert replica.state == ms.DRAINING
            transitions = [(e["state"], e["reason"])
                           for e in _events(journal, "fleet_member")]
            assert transitions[0] == ("live", "joined")
            assert len(transitions) == 2
            assert transitions[1][0] == "draining"
            assert transitions[1][1].startswith(
                "heartbeat_stale:serve_idle")
            write_beat(0.0)  # the worker recovers
            membership.poll_once()
            assert replica.state == ms.LIVE
        finally:
            fake.stop()


class TestRouter:
    def test_least_loaded_dispatch(self, journal):
        busy, idle = FakeReplica(), FakeReplica()
        busy.queue_depth = 50
        try:
            _, membership, router = _fleet([busy, idle], journal)
            membership.poll_once()
            for _ in range(5):
                status, _, replica_id = router.dispatch(b"{}")
                assert status == 200
                assert replica_id == "r1"  # the idle one, every time
            assert idle.predict_count() == 5
            assert busy.predict_count() == 0
        finally:
            busy.stop()
            idle.stop()

    def test_dead_replica_fails_over_with_zero_failures(self, journal):
        dying, healthy = FakeReplica(), FakeReplica()
        try:
            replicas, membership, router = _fleet([dying, healthy], journal)
            membership.poll_once()
            dying.queue_depth = 0
            dying.stop()  # dies AFTER membership saw it live
            for _ in range(8):
                status, _, _ = router.dispatch(b"{}")
                assert status == 200  # every request lands on the sibling
            assert replicas[0].state == ms.OUT  # pulled at first dead conn
            assert router.n_failovers >= 1
            retries = _events(journal, "fleet_retry")
            assert retries and retries[0]["replica"] == "r0"
        finally:
            healthy.stop()

    def test_all_busy_is_429_no_live_is_503(self, journal):
        fake = FakeReplica()
        try:
            replicas, membership, router = _fleet([fake], journal)
            membership.poll_once()
            fake.predict_status = 429
            with pytest.raises(AllReplicasBusy):
                router.dispatch(b"{}")
            membership.set_state(replicas[0], ms.OUT, "test")
            with pytest.raises(NoLiveReplicas):
                router.dispatch(b"{}")
        finally:
            fake.stop()

    def test_5xx_failover_trips_the_replica_breaker(self, journal):
        from eegnetreplication_tpu.resil.breaker import CircuitBreaker

        broken, healthy = FakeReplica(), FakeReplica()
        try:
            replicas = [
                ms.Replica("r0", broken.url, journal=journal,
                           breaker=CircuitBreaker(failure_threshold=3,
                                                  site="fleet.r0",
                                                  journal=journal)),
                ms.Replica("r1", healthy.url, journal=journal)]
            membership = ms.FleetMembership(replicas, journal=journal)
            router = FleetRouter(membership, journal=journal)
            membership.poll_once()
            broken.predict_status = 500
            broken.queue_depth = 0
            healthy.queue_depth = 10  # force r0 to be tried first
            for _ in range(6):
                status, _, replica_id = router.dispatch(b"{}")
                assert status == 200 and replica_id == "r1"
            # Three 500s opened r0's breaker; later dispatches skip it.
            assert replicas[0].breaker.state == "open"
            assert broken.predict_count() == 3
        finally:
            broken.stop()
            healthy.stop()


class TestRollingReload:
    def _seed_ring(self, router, n=4):
        for _ in range(n):
            status, _, _ = router.dispatch(b"{}")
            assert status == 200

    def test_converges_and_journals_shadow(self, journal):
        fakes = [FakeReplica() for _ in range(3)]
        try:
            _, membership, router = _fleet(fakes, journal)
            membership.poll_once()
            self._seed_ring(router)
            result = RollingReload(router, "new.npz",
                                   previous_checkpoint="old.npz",
                                   shadow_n=3, journal=journal).run()
            assert result["status"] == "converged"
            assert result["new_digest"] == "d-new"
            assert result["shadow"]["n"] == 3
            assert result["shadow"]["agree"] == 1.0
            assert all(f.digest == "d-new" for f in fakes)
            assert len(result["rolled"]) == 3
            shadows = _events(journal, "fleet_shadow")
            assert len(shadows) == 3
            assert all(e["agree"] == 1.0 for e in shadows)
            reloads = _events(journal, "fleet_reload")
            assert reloads[-1]["status"] == "converged"
        finally:
            for f in fakes:
                f.stop()

    def test_corrupt_push_leaves_whole_fleet_on_old_digest(self, journal):
        fakes = [FakeReplica() for _ in range(3)]
        for f in fakes:
            f.reload_fn = lambda ck: (400, "IntegrityError: sha mismatch")
        try:
            _, membership, router = _fleet(fakes, journal)
            membership.poll_once()
            self._seed_ring(router)
            result = RollingReload(router, "corrupt.npz",
                                   previous_checkpoint="old.npz",
                                   journal=journal).run()
            assert result["status"] == "failed"
            assert result["stage"] == "canary_reload"
            assert all(f.digest == "d-old" for f in fakes)
            # Exactly ONE replica (the canary) ever saw the bad push.
            assert sum(len(f.reload_checkpoints()) for f in fakes) == 1
            membership.poll_once()
            assert len(membership.dispatchable()) == 3  # canary rejoined
        finally:
            for f in fakes:
                f.stop()

    def test_shadow_disagreement_rolls_canary_back(self, journal):
        fakes = [FakeReplica() for _ in range(3)]

        def scripted_reload(fake):
            def fn(ck):
                if ck == "new.npz":
                    # The new model answers differently; healthz digest
                    # follows the swap, as the real replica's would.
                    fake.predictions = [3, 3, 3]
                    return 200, "d-new"
                fake.predictions = [0, 1, 2]   # rollback restores it
                return 200, "d-old"
            return fn

        for f in fakes:
            f.reload_fn = scripted_reload(f)
        try:
            _, membership, router = _fleet(fakes, journal)
            membership.poll_once()
            self._seed_ring(router)
            result = RollingReload(router, "new.npz",
                                   previous_checkpoint="old.npz",
                                   shadow_n=3, agree_floor=0.9,
                                   journal=journal).run()
            assert result["status"] == "failed"
            assert result["stage"] == "shadow"
            assert result["shadow"]["agree"] == 0.0
            # The canary was rolled back; nobody else was ever touched.
            assert all(f.digest == "d-old" for f in fakes)
            canary_reloads = [ck for f in fakes
                              for ck in f.reload_checkpoints()]
            assert sorted(canary_reloads) == ["new.npz", "old.npz"]
            phases = [e["phase"] for e in _events(journal, "fleet_canary")]
            assert "shadow_fail" in phases and "rolled_back" in phases
        finally:
            for f in fakes:
                f.stop()

    def test_unverifiable_digest_aborts(self, journal):
        # The reload response claims d-new but /healthz keeps showing
        # d-old: identity cannot be verified, so nothing else is rolled.
        fakes = [FakeReplica() for _ in range(2)]
        for f in fakes:
            f.healthz_digest = "d-old"
        try:
            _, membership, router = _fleet(fakes, journal)
            membership.poll_once()
            result = RollingReload(router, "new.npz",
                                   previous_checkpoint="old.npz",
                                   journal=journal).run()
            assert result["status"] == "failed"
            assert result["stage"] == "digest_verify"
            phases = [e["phase"] for e in _events(journal, "fleet_canary")]
            assert "digest_mismatch" in phases
            # Only the canary saw /reload traffic (its push + rollback).
            touched = [f for f in fakes if f.reload_checkpoints()]
            assert len(touched) == 1
        finally:
            for f in fakes:
                f.stop()

    def test_event_summary_reports_fleet_fields(self, journal):
        fakes = [FakeReplica() for _ in range(2)]
        try:
            _, membership, router = _fleet(fakes, journal)
            membership.poll_once()
            self._seed_ring(router)
            RollingReload(router, "new.npz", previous_checkpoint="old.npz",
                          shadow_n=2, journal=journal).run()
        finally:
            for f in fakes:
                f.stop()
        events = schema.read_events(journal.events_path, complete=False)
        summary = schema.event_summary(events)
        assert summary["fleet_member_transitions"] >= 2
        assert summary["fleet_reload_status"] == "converged"
        assert summary["fleet_shadow_agree"] == 1.0
        assert not any("_schema_error" in e for e in events)


class TestCheckpointReconciliation:
    def test_converged_reload_updates_supervised_launch_commands(self):
        """A crash-relaunch after a converged rolling reload must come
        back on the NEW checkpoint — the supervisor's child commands are
        rewritten by the on_checkpoint_change hook."""
        from eegnetreplication_tpu.resil import supervise
        from eegnetreplication_tpu.serve.fleet.service import (
            update_child_checkpoints,
        )

        specs = [supervise.ChildSpec(
            name=f"r{i}",
            cmd=[sys.executable, "-m", "eegnetreplication_tpu.serve",
                 "--checkpoint", "old.npz", "--port", str(9000 + i)])
            for i in range(3)]
        sup = supervise.MultiSupervisor(specs)
        update_child_checkpoints(sup, "new.npz")
        for child in sup.children.values():
            cmd = child.spec.cmd
            assert cmd[cmd.index("--checkpoint") + 1] == "new.npz"
            assert "old.npz" not in cmd


class TestFleetSelftest:
    def test_fleet_selftest_passes(self, tmp_path):
        """ISSUE-6 acceptance, end to end with real processes: open-loop
        rps scales >= 0.8x linear to 4 replicas on CPU, a SIGKILLed
        replica under load costs zero failed requests and rejoins, the
        rolling canary converges the fleet to the new digest with shadow
        compares journaled, and a corrupt push changes nothing."""
        out = tmp_path / "BENCH_FLEET_selftest.json"
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "serve_bench.py"),
             "--fleet", "4", "--selftest", "--out", str(out),
             "--traceSample", "0.25"],
            capture_output=True, text=True, timeout=420,
            env=dict(os.environ, EEGTPU_NO_LOG_FILE="1",
                     EEGTPU_PLATFORM="cpu"))
        assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
        assert "SELFTEST PASS" in proc.stdout
        record = json.loads(out.read_text())
        assert record["linear_fraction"] >= 0.8
        assert record["kill_leg"]["failures"] == 0
        assert record["kill_leg"]["rejoined"] is True
        assert record["reload_leg"]["reload"]["status"] == "converged"
        assert record["reload_leg"]["load"]["failures"] == 0
        assert record["failed_canary_leg"]["digests_unchanged"] is True
        assert record["journal"]["fleet_shadow_events"] >= 1
        assert record["http_smoke"]["ok"] is True
        # ISSUE-9 acceptance: sampled requests through the real 4-replica
        # fleet reconstruct as complete cross-process trace trees from
        # the router + replica journals alone.
        assert record["trace"]["complete_traces"] >= 1


class TestFleetTracing:
    """PR 9: the router is the trace edge — spans journal under one
    trace id, failover retries become child spans, and propagation
    headers reach the replica that actually served the request."""

    def _spans(self, jr):
        return [e for e in schema.read_events(jr.events_path,
                                              complete=False)
                if e["event"] == "span"]

    def test_dispatch_propagates_trace_headers(self, journal):
        from eegnetreplication_tpu.obs import trace

        fake = FakeReplica()
        try:
            _, membership, router = _fleet([fake], journal)
            membership.poll_once()
            ctx = trace.TraceContext(trace.new_trace_id(), sampled=True)
            with trace.use(ctx):
                status, _, _ = router.dispatch(b"{}")
            assert status == 200
            sent = fake.headers_log[-1]
            assert sent["X-Trace-Id"] == ctx.trace_id
            assert sent["X-Trace-Sampled"] == "1"
            spans = self._spans(journal)
            dispatch = [s for s in spans
                        if s["name"] == "router.dispatch"][0]
            # The replica's parent is the dispatch span (no failover).
            assert sent["X-Parent-Span"] == dispatch["span_id"]
            assert dispatch["replica"] == "r0"
            assert dispatch["attempts"] == 1
        finally:
            fake.stop()

    def test_untraced_dispatch_sends_no_headers_no_spans(self, journal):
        fake = FakeReplica()
        try:
            _, membership, router = _fleet([fake], journal)
            membership.poll_once()
            status, _, _ = router.dispatch(b"{}")
            assert status == 200
            sent = fake.headers_log[-1]
            assert "X-Trace-Id" not in sent
            assert self._spans(journal) == []
        finally:
            fake.stop()

    def test_failover_produces_retry_child_span_same_trace(self, journal):
        """ISSUE-9 satellite: a failover dispatch yields a router.retry
        CHILD span on the same trace_id, and the surviving replica's
        propagated parent is the RETRY span (the attempt that reached
        it)."""
        from eegnetreplication_tpu.obs import trace

        dying, healthy = FakeReplica(), FakeReplica()
        try:
            replicas, membership, router = _fleet([dying, healthy],
                                                  journal)
            membership.poll_once()
            dying.queue_depth = 0
            healthy.queue_depth = 10  # force the dying one to be tried
            dying.stop()              # dies AFTER membership saw it live
            ctx = trace.TraceContext(trace.new_trace_id(), sampled=True)
            with trace.use(ctx):
                status, _, replica_id = router.dispatch(b"{}")
            assert status == 200 and replica_id == "r1"
            spans = self._spans(journal)
            by_name = {s["name"]: s for s in spans}
            dispatch = by_name["router.dispatch"]
            retry = by_name["router.retry"]
            assert retry["trace_id"] == dispatch["trace_id"] \
                == ctx.trace_id
            assert retry["parent_span_id"] == dispatch["span_id"]
            assert retry["replica"] == "r1"
            # The replica that answered saw the retry span as parent.
            sent = healthy.headers_log[-1]
            assert sent["X-Parent-Span"] == retry["span_id"]
            assert sent["X-Trace-Id"] == ctx.trace_id
            # And the stitcher reconstructs dispatch -> retry as a tree.
            trees = trace.build_traces(spans)
            tree = trees[ctx.trace_id]
            assert [s["name"] for s in tree.roots] == ["router.dispatch"]
            assert [s["name"] for s in
                    tree.children[dispatch["span_id"]]] == ["router.retry"]
        finally:
            healthy.stop()


class TestFleetSLOAggregation:
    def test_replica_slo_state_mirrors_into_snapshot(self, journal):
        """Each replica's /healthz-advertised SLO breaches flow through
        the membership poll into the snapshot the fleet /healthz
        aggregates."""
        fake = FakeReplica()
        fake.slo_breached = ["p95_latency_ms<50"]
        try:
            replicas, membership, _ = _fleet([fake], journal)
            membership.poll_once()
            assert replicas[0].slo_breached == ["p95_latency_ms<50"]
            snap = membership.snapshot()[0]
            assert snap["slo_breached"] == ["p95_latency_ms<50"]
            fake.slo_breached = []
            membership.poll_once()
            assert membership.snapshot()[0]["slo_breached"] == []
        finally:
            fake.stop()
