"""Subprocess tests of the CLI plugin boundary.

The GUI drives the framework exclusively through
``python -m eegnetreplication_tpu.{fetch,dataset,train}`` subprocesses (the
reference's architectural keystone, ``ui.py:213,229,256-259``); these tests
exercise that exact boundary end-to-end on a synthetic data tree.
"""

import json
import os
import subprocess
import sys
import tempfile
import unittest
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parents[1]


def _run(args, data_root, timeout=420, env_extra=None):
    env = dict(os.environ,
               EEGTPU_DATA_ROOT=str(data_root),
               EEGTPU_PLATFORM="cpu",
               EEGTPU_NO_LOG_FILE="1",
               PYTHONPATH=str(REPO))
    env.update(env_extra or {})
    return subprocess.run([sys.executable, "-m"] + args, cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=timeout)


class TestCLIBoundary(unittest.TestCase):
    @classmethod
    def setUpClass(cls):
        from scipy.io import savemat

        from eegnetreplication_tpu.config import Paths
        from eegnetreplication_tpu.data.gdf import write_gdf

        cls.tmp = Path(tempfile.mkdtemp(prefix="eegtpu_cli_"))
        paths = Paths.from_root(cls.tmp)
        rng = np.random.RandomState(0)
        n = 250 * 40
        for s in (1, 2):
            for mode, sess in (("Train", "T"), ("Eval", "E")):
                sig = rng.uniform(-0.5, 0.5, (25, n)).astype(np.float32)
                pos = np.arange(8) * 1100 + 300
                typ = (np.array([769, 770, 771, 772] * 2) if mode == "Train"
                       else np.full(8, 783))
                write_gdf(paths.data_raw / mode / f"A{s:02d}{sess}.gdf", sig,
                          250.0, event_pos=pos, event_typ=typ)
                if mode == "Eval":
                    (paths.data_raw / "TrueLabels").mkdir(exist_ok=True)
                    savemat(paths.data_raw / "TrueLabels" / f"A{s:02d}E.mat",
                            {"classlabel": rng.randint(1, 5, 8)})

    @classmethod
    def tearDownClass(cls):
        import shutil

        shutil.rmtree(cls.tmp, ignore_errors=True)

    def test_0_train_help_lists_telemetry_flag(self):
        """`train --help` is the cheapest CI probe that the CLI imports and
        the telemetry flag is wired."""
        proc = _run(["eegnetreplication_tpu.train", "--help"], self.tmp,
                    timeout=120)
        self.assertEqual(proc.returncode, 0, proc.stderr[-2000:])
        self.assertIn("--metricsDir", proc.stdout)
        self.assertIn("--trainingType", proc.stdout)

    def test_1_dataset_cli(self):
        proc = _run(["eegnetreplication_tpu.dataset", "--src", "kaggle"],
                    self.tmp)
        self.assertEqual(proc.returncode, 0, proc.stderr[-2000:])
        processed = self.tmp / "data" / "processed"
        for s in (1, 2):
            self.assertTrue(
                (processed / "Train" / f"A{s:02d}T-trials.npz").exists())
            self.assertTrue(
                (processed / "Eval" / f"A{s:02d}E-trials.npz").exists())

    def test_2_train_cli_writes_report_and_models(self):
        proc = _run(["eegnetreplication_tpu.train",
                     "--trainingType", "Within-Subject", "--epochs", "1",
                     "--subjects", "1,2", "--generateReport", "True"],
                    self.tmp)
        self.assertEqual(proc.returncode, 0, proc.stderr[-2000:])
        report_path = (self.tmp / "reports"
                       / "latest_within_subject_report.json")
        self.assertTrue(report_path.exists())
        report = json.loads(report_path.read_text())
        self.assertEqual(report["training_type"], "Within-Subject")
        self.assertEqual(
            [r["subject_id"] for r in report["per_subject_results"]], [1, 2])
        self.assertTrue(
            (self.tmp / "models" / "subject_01_best_model.npz").exists())

    def test_2b_train_cli_writes_telemetry(self):
        """The ISSUE-1 acceptance path: a 1-epoch, 1-subject CPU run with
        --metricsDir yields a schema-valid events.jsonl (run_start, >=1
        epoch event with loss and grad-norm, run_end) and metrics.json."""
        from eegnetreplication_tpu.obs import schema

        obs_dir = self.tmp / "obs_cli"
        proc = _run(["eegnetreplication_tpu.train",
                     "--trainingType", "Within-Subject", "--epochs", "1",
                     "--subjects", "1", "--generateReport", "False",
                     "--metricsDir", str(obs_dir)],
                    self.tmp)
        self.assertEqual(proc.returncode, 0, proc.stderr[-2000:])
        runs = [d for d in obs_dir.iterdir()
                if (d / "events.jsonl").exists()]
        self.assertEqual(len(runs), 1, runs)
        events = schema.read_events(runs[0] / "events.jsonl")
        kinds = [e["event"] for e in events]
        self.assertEqual(kinds[0], "run_start")
        self.assertEqual(kinds[-1], "run_end")
        self.assertNotIn("_schema_error",
                         {k for e in events for k in e})
        self.assertEqual(events[-1]["status"], "ok")
        epochs = [e for e in events if e["event"] == "epoch"]
        self.assertGreaterEqual(len(epochs), 1)
        self.assertTrue(all("train_loss" in e and "grad_norm" in e
                            for e in epochs))
        metrics = schema.read_metrics(runs[0] / "metrics.json")
        self.assertIn("fold_epochs_total", metrics["counters"])
        self.assertIn("epoch_throughput", metrics["gauges"])

    @pytest.mark.slow
    def test_3_generate_report_false_writes_nothing(self):
        # Quirk Q5: the reference's `--generateReport False` still wrote a
        # report; ours must not.  Telemetry goes to an explicit metricsDir
        # outside reports/ so the run-journal default (reports/obs) does not
        # shadow the report-writing invariant under test.
        before = set((self.tmp / "reports").glob("*")) \
            if (self.tmp / "reports").exists() else set()
        proc = _run(["eegnetreplication_tpu.train",
                     "--trainingType", "Within-Subject", "--epochs", "1",
                     "--subjects", "1", "--generateReport", "False",
                     "--metricsDir", str(self.tmp / "obs_q5")],
                    self.tmp)
        self.assertEqual(proc.returncode, 0, proc.stderr[-2000:])
        after = set((self.tmp / "reports").glob("*")) \
            if (self.tmp / "reports").exists() else set()
        self.assertEqual(before, after)

    @pytest.mark.slow
    def test_4_train_cli_data_axis(self):
        """--meshData 2 composes within-fold DP with the fold sharding on
        the virtual 8-device mesh (conftest's XLA_FLAGS is inherited)."""
        proc = _run(["eegnetreplication_tpu.train",
                     "--trainingType", "Within-Subject", "--epochs", "2",
                     "--generateReport", "False", "--meshFold", "4",
                     "--meshData", "2", "--subjects", "1,2"],
                    self.tmp, timeout=600)
        self.assertEqual(proc.returncode, 0, proc.stderr[-2000:])
        self.assertIn("'data': 2", proc.stderr + proc.stdout)

    @pytest.mark.slow
    def test_5_train_cli_convnet_model(self):
        """The ConvNet baselines run the full protocol end-to-end through
        the CLI registry switch (VERDICT round-1 item 8)."""
        ckpt = self.tmp / "models" / "subject_01_best_model.npz"
        ckpt.unlink(missing_ok=True)  # test_2 wrote an eegnet one
        proc = _run(["eegnetreplication_tpu.train",
                     "--trainingType", "Within-Subject", "--epochs", "1",
                     "--subjects", "1", "--generateReport", "False",
                     "--model", "shallow_convnet"],
                    self.tmp, timeout=600)
        self.assertEqual(proc.returncode, 0, proc.stderr[-2000:])
        self.assertTrue(ckpt.exists())
        from eegnetreplication_tpu.training.checkpoint import load_checkpoint

        _, _, meta = load_checkpoint(ckpt)
        self.assertEqual(meta["model"], "shallow_convnet")

    @pytest.mark.slow
    def test_5b_train_cli_fold_batching(self):
        # Single-device env: under a multi-device mesh the flag is
        # (by design) ignored in favour of fold sharding.
        proc = _run(["eegnetreplication_tpu.train",
                     "--trainingType", "Within-Subject", "--epochs", "1",
                     "--subjects", "1", "--maxFoldsPerProgram", "2",
                     "--generateReport", "False"],
                    self.tmp, env_extra={"XLA_FLAGS": ""})
        self.assertEqual(proc.returncode, 0, proc.stderr[-2000:])
        # 4 folds in groups of 2 -> two group logs
        self.assertEqual(proc.stderr.count("Training fold group"), 2)

    def test_6_predict_cli(self):
        """Inference CLI classifies a session with a trained checkpoint."""
        ckpt = self.tmp / "models" / "subject_01_best_model.npz"
        self.assertTrue(ckpt.exists(), "train test must run first")
        proc = _run(["eegnetreplication_tpu.predict",
                     "--checkpoint", str(ckpt),
                     "--subject", "1", "--mode", "Eval"],
                    self.tmp, timeout=420)
        self.assertEqual(proc.returncode, 0, proc.stderr[-2000:])
        self.assertIn("accuracy", proc.stdout + proc.stderr)

    def test_fetch_cli_errors_cleanly_without_backend(self):
        proc = _run(["eegnetreplication_tpu.fetch", "--src", "kaggle"],
                    self.tmp, timeout=120)
        if proc.returncode != 0:  # kagglehub absent in this environment
            self.assertIn("kagglehub", proc.stderr)

    def test_dataset_cli_rejects_unknown_src(self):
        proc = _run(["eegnetreplication_tpu.dataset", "--src", "nope"],
                    self.tmp, timeout=120)
        self.assertNotEqual(proc.returncode, 0)
        self.assertIn("Unknown source", proc.stderr)



class TestFetchHelpers(unittest.TestCase):
    def test_mirror_into_copies_and_replaces(self):
        """Files copy over; existing directories are replaced wholesale."""
        from eegnetreplication_tpu.fetch import _mirror_into

        with tempfile.TemporaryDirectory() as td:
            src = Path(td) / "cache"
            (src / "Train").mkdir(parents=True)
            (src / "Train" / "A01T.gdf").write_bytes(b"new")
            (src / "readme.txt").write_text("hello")
            dst = Path(td) / "raw"
            (dst / "Train").mkdir(parents=True)
            (dst / "Train" / "stale.gdf").write_bytes(b"old")
            _mirror_into(src, dst)
            self.assertEqual((dst / "Train" / "A01T.gdf").read_bytes(), b"new")
            self.assertFalse((dst / "Train" / "stale.gdf").exists())
            self.assertEqual((dst / "readme.txt").read_text(), "hello")

    def test_mirror_into_replaces_shape_mismatches(self):
        """A file where the cache has a dir (and vice versa) is replaced."""
        from eegnetreplication_tpu.fetch import _mirror_into

        with tempfile.TemporaryDirectory() as td:
            src = Path(td) / "cache"
            (src / "Train").mkdir(parents=True)
            (src / "Train" / "A01T.gdf").write_bytes(b"new")
            (src / "notes").write_text("now a file")
            dst = Path(td) / "raw"
            dst.mkdir()
            (dst / "Train").write_text("file where a dir belongs")
            (dst / "notes").mkdir()
            (dst / "notes" / "stale").write_text("dir where a file belongs")
            _mirror_into(src, dst)
            self.assertEqual((dst / "Train" / "A01T.gdf").read_bytes(), b"new")
            self.assertEqual((dst / "notes").read_text(), "now a file")

if __name__ == "__main__":
    unittest.main()
