"""Protocol + report tests on synthetic data.

Validates the full within/cross-subject orchestration (fold construction,
vmapped training, best-model selection, model saving) and byte-level report
schema parity with the reference's ``generate_*_report``.
"""

import json

import numpy as np
import pytest

from eegnetreplication_tpu.config import DEFAULT_TRAINING, Paths
from eegnetreplication_tpu.training.protocols import (
    cross_subject_training,
    within_subject_training,
)
from eegnetreplication_tpu.training.report import (
    generate_cs_report,
    generate_ws_report,
)
from synthetic import make_loader

CFG = DEFAULT_TRAINING.replace(batch_size=16)


@pytest.fixture
def tmp_paths(tmp_path):
    return Paths.from_root(tmp_path)


class TestWithinSubject:
    def test_three_subjects_end_to_end(self, tmp_paths):
        loader = make_loader(n_trials=32, n_channels=6, n_times=64,
                             class_sep=1.5)
        result = within_subject_training(
            epochs=25, config=CFG, loader=loader, subjects=(1, 2, 3),
            paths=tmp_paths, seed=0)
        assert len(result.per_subject_test_acc) == 3
        assert result.fold_test_acc.shape == (12,)
        assert result.fold_min_val_loss.shape == (12,)
        assert np.all(np.isfinite(result.fold_min_val_loss))
        assert np.isclose(result.avg_test_acc,
                          np.mean(result.per_subject_test_acc))
        # separable synthetic task: better than the 25% chance level
        assert result.avg_test_acc > 40.0
        for s in (1, 2, 3):
            assert (tmp_paths.models / f"subject_{s:02d}_best_model.pth").exists()
            assert (tmp_paths.models / f"subject_{s:02d}_best_model.npz").exists()

    def test_report_schema_matches_reference(self, tmp_paths):
        loader = make_loader(n_trials=24, n_channels=4, n_times=64)
        result = within_subject_training(
            epochs=2, config=CFG, loader=loader, subjects=(1, 2),
            paths=tmp_paths, seed=0)
        generate_ws_report(result.per_subject_test_acc, result.avg_test_acc,
                           result.best_states, epochs=2, config=CFG,
                           paths=tmp_paths)
        with open(tmp_paths.reports / "latest_within_subject_report.json") as f:
            report = json.load(f)
        assert set(report) == {
            "training_type", "timestamp", "model_parameters",
            "overall_results", "per_subject_results", "model_info",
            "summary_statistics"}
        assert report["training_type"] == "Within-Subject"
        assert set(report["model_parameters"]) == {
            "batch_size", "epochs", "learning_rate", "dropout_probability",
            "cross_validation_folds"}
        assert set(report["overall_results"]) == {
            "average_test_accuracy", "number_of_subjects",
            "best_subject_accuracy", "worst_subject_accuracy", "accuracy_std"}
        entry = report["per_subject_results"][0]
        assert set(entry) == {"subject_id", "test_accuracy", "model_saved",
                              "performance_rank"}
        assert entry["model_saved"] == "subject_01_best_model.pth"
        ranks = sorted(e["performance_rank"]
                       for e in report["per_subject_results"])
        assert ranks == [1, 2]
        assert set(report["summary_statistics"]) == {
            "accuracy_distribution", "accuracy_quartiles"}


class TestCrossSubject:
    def test_four_subjects_end_to_end(self, tmp_paths):
        loader = make_loader(n_trials=24, n_channels=4, n_times=64)
        cfg = CFG.replace(cs_repeats_per_subject=2, cs_train_subjects=2,
                          cs_val_subjects=1)
        result = cross_subject_training(
            epochs=4, config=cfg, loader=loader, subjects=(1, 2, 3, 4),
            paths=tmp_paths, seed=0)
        assert len(result.per_subject_test_acc) == 4
        assert result.fold_test_acc.shape == (8,)  # 4 subjects x 2 repeats
        assert (tmp_paths.models / "cross_subject_best_model.pth").exists()
        assert len(result.best_states) == 1

    def test_report_schema_matches_reference(self, tmp_paths):
        accs = [55.0, 60.0, 65.0]
        generate_cs_report(None, accs, 60.0, epochs=4, config=CFG,
                           paths=tmp_paths)
        with open(tmp_paths.reports / "latest_cross_subject_report.json") as f:
            report = json.load(f)
        assert report["training_type"] == "Cross-Subject"
        assert set(report["model_parameters"]) == {
            "batch_size", "epochs", "learning_rate", "dropout_probability",
            "total_folds", "repeats_per_subject", "train_subjects_per_fold",
            "validation_subjects_per_fold"}
        assert set(report["overall_results"]) == {
            "average_test_accuracy", "standard_error",
            "number_of_test_subjects", "best_subject_accuracy",
            "worst_subject_accuracy", "accuracy_std"}
        entry = report["per_subject_results"][0]
        assert set(entry) == {"test_subject_id", "test_accuracy",
                              "performance_rank"}
        assert report["overall_results"]["standard_error"] == round(
            float(np.std(accs) / np.sqrt(3)), 2)
        assert report["model_info"]["saved_model"] == "cross_subject_best_model.pth"


class TestChunkedResume:
    """Mid-run checkpointing: chunked scans + crash/resume (SURVEY §5)."""

    def _run(self, tmp_paths, **kw):
        loader = make_loader(n_trials=24, n_channels=4, n_times=64)
        return within_subject_training(
            epochs=6, config=CFG, loader=loader, subjects=(1,),
            paths=tmp_paths, seed=0, save_models=False, **kw)

    def test_chunked_matches_fused(self, tmp_paths):
        """Segmenting the epoch scan must be bit-identical to one program."""
        fused = self._run(tmp_paths)
        chunked = self._run(tmp_paths, checkpoint_every=2)
        np.testing.assert_array_equal(chunked.fold_test_acc,
                                      fused.fold_test_acc)
        for a, b in zip(chunked.best_states, fused.best_states):
            for la, lb in zip(*(map(np.asarray, __import__("jax").tree_util
                                    .tree_leaves(t)) for t in (a, b))):
                np.testing.assert_array_equal(la, lb)
        # completed run cleans up its snapshot
        assert not (tmp_paths.models / "within_subject_eegnet.run.npz").exists()

    def test_epoch_cadence_lines_logged(self, tmp_paths, caplog):
        """Reference-style epoch lines (model.py:185-187) appear while
        training: epoch 1 and the last epoch, live after each chunk."""
        import logging

        with caplog.at_level(logging.INFO):
            self._run(tmp_paths, checkpoint_every=2)
        lines = [r.getMessage() for r in caplog.records
                 if r.getMessage().startswith("Epoch: ")]
        assert any(line.startswith("Epoch: 1/6.. Train Loss: ")
                   for line in lines), lines
        assert any(line.startswith("Epoch: 6/6.. ") for line in lines), lines
        assert all("Val Loss: " in line and "Val Acc: " in line
                   for line in lines)

    def test_epoch_cadence_lines_logged_fused(self, tmp_paths, caplog):
        """The single-program path logs the same cadence post-hoc."""
        import logging

        with caplog.at_level(logging.INFO):
            self._run(tmp_paths, checkpoint_every=0)
        lines = [r.getMessage() for r in caplog.records
                 if r.getMessage().startswith("Epoch: ")]
        assert any(line.startswith("Epoch: 1/6.. ") for line in lines), lines
        assert any(line.startswith("Epoch: 6/6.. ") for line in lines), lines

    def test_crash_and_resume_bit_identical(self, tmp_paths):
        """Kill after the first chunk; --resume completes to the same result."""
        uninterrupted = self._run(tmp_paths, checkpoint_every=2)
        with pytest.raises(RuntimeError, match="injected crash"):
            self._run(tmp_paths, checkpoint_every=2, _crash_after_chunk=1)
        snap = tmp_paths.models / "within_subject_eegnet.run.npz"
        assert snap.exists()
        resumed = self._run(tmp_paths, checkpoint_every=2, resume=True)
        np.testing.assert_array_equal(resumed.fold_test_acc,
                                      uninterrupted.fold_test_acc)
        assert not snap.exists()

    def test_stale_snapshot_rejected(self, tmp_paths):
        """A snapshot from a different run must refuse to resume."""
        with pytest.raises(RuntimeError, match="injected crash"):
            self._run(tmp_paths, checkpoint_every=2, _crash_after_chunk=1)
        loader = make_loader(n_trials=24, n_channels=4, n_times=64)
        with pytest.raises(ValueError, match="different run"):
            within_subject_training(
                epochs=4, config=CFG, loader=loader, subjects=(1,),
                paths=tmp_paths, seed=0, save_models=False,
                checkpoint_every=2, resume=True)

    def test_content_mismatch_resumes_fresh(self, tmp_paths, caplog):
        """Same geometry, different data content (pool digest mismatch):
        --resume downgrades to a fresh run with a warning — the graceful
        outcome the rehearsal's geometry-only gate relies on — instead of
        splicing datasets or hard-failing (ADVICE r3 / review r4)."""
        import logging

        with pytest.raises(RuntimeError, match="injected crash"):
            self._run(tmp_paths, checkpoint_every=2, _crash_after_chunk=1)
        snap = tmp_paths.models / "within_subject_eegnet.run.npz"
        assert snap.exists()
        # Identical geometry, different trial values.
        loader2 = make_loader(n_trials=24, n_channels=4, n_times=64,
                              class_sep=1.7)
        with caplog.at_level(logging.WARNING):
            result = within_subject_training(
                epochs=6, config=CFG, loader=loader2, subjects=(1,),
                paths=tmp_paths, seed=0, save_models=False,
                checkpoint_every=2, resume=True)
        assert any("not its data content" in r.getMessage()
                   for r in caplog.records)
        # Fresh run to completion over the new data; snapshot cleaned up.
        assert len(result.per_subject_test_acc) == 1
        assert not snap.exists()

    @pytest.mark.slow
    def test_legacy_snapshot_without_digest_resumes(self, tmp_paths, caplog):
        """A pre-digest (legacy) snapshot whose geometry matches resumes —
        content is unverifiable, and discarding an in-flight run's progress
        on the first post-upgrade invocation is the worse failure; only a
        PROVEN digest mismatch downgrades to fresh (ADVICE r4)."""
        import json
        import logging

        uninterrupted = self._run(tmp_paths, checkpoint_every=2)
        with pytest.raises(RuntimeError, match="injected crash"):
            self._run(tmp_paths, checkpoint_every=2, _crash_after_chunk=1)
        snap = tmp_paths.models / "within_subject_eegnet.run.npz"
        # Strip pool_sha1 from the stored signature in place: the snapshot
        # a pre-digest build would have written.
        with np.load(snap, allow_pickle=False) as data:
            flat = {k: data[k] for k in data.files}
        sig = json.loads(bytes(flat["__signature__"]).decode())
        assert sig.pop("pool_sha1", None) is not None
        flat["__signature__"] = np.frombuffer(
            json.dumps(sig, sort_keys=True).encode(), dtype=np.uint8)
        with open(snap, "wb") as fh:
            np.savez(fh, **flat)
        with caplog.at_level(logging.WARNING):
            resumed = self._run(tmp_paths, checkpoint_every=2, resume=True)
        assert any("predates pool digests" in r.getMessage()
                   for r in caplog.records)
        np.testing.assert_array_equal(resumed.fold_test_acc,
                                      uninterrupted.fold_test_acc)
        assert not snap.exists()

    def test_numerics_change_rejected_on_resume(self, tmp_paths):
        """Resuming a carry under different numerics or update rules would
        silently change the science — the signature must refuse."""
        with pytest.raises(RuntimeError, match="injected crash"):
            self._run(tmp_paths, checkpoint_every=2, _crash_after_chunk=1)
        for cfg in (CFG.replace(precision="bf16"),
                    CFG.replace(maxnorm_mode="paper")):
            loader = make_loader(n_trials=24, n_channels=4, n_times=64)
            with pytest.raises(ValueError, match="different run"):
                within_subject_training(
                    epochs=6, config=cfg, loader=loader, subjects=(1,),
                    paths=tmp_paths, seed=0, save_models=False,
                    checkpoint_every=2, resume=True)


class TestPrecisionModes:
    """The TPU numerics knob: 'highest' (parity default) vs 'default'/'bf16'."""

    def test_model_kwargs_mapping(self):
        import jax.numpy as jnp

        from eegnetreplication_tpu.training.protocols import (
            _model_kwargs_for_precision,
        )

        assert _model_kwargs_for_precision(CFG) == {}
        assert (_model_kwargs_for_precision(CFG.replace(precision="high"))
                == {"precision": "high"})
        assert (_model_kwargs_for_precision(CFG.replace(precision="default"))
                == {"precision": None})
        bf16 = _model_kwargs_for_precision(CFG.replace(precision="bf16"))
        assert bf16 == {"precision": None, "dtype": jnp.bfloat16}
        with pytest.raises(ValueError, match="precision"):
            _model_kwargs_for_precision(CFG.replace(precision="fp8"))

    @pytest.mark.parametrize("mode", ["default", "bf16"])
    def test_protocol_trains_and_learns(self, tmp_paths, mode):
        """Reduced-precision runs stay finite and beat chance on an easy
        separable task (trajectories differ from f32 by design)."""
        loader = make_loader(n_trials=32, n_channels=6, n_times=64,
                             class_sep=1.5)
        result = within_subject_training(
            epochs=25, config=CFG.replace(precision=mode), loader=loader,
            subjects=(1,), paths=tmp_paths, seed=0, save_models=False)
        assert np.isfinite(result.avg_test_acc)
        assert result.avg_test_acc > 40.0


class TestOrbaxArtifacts:
    def test_ws_protocol_saves_orbax_directories(self, tmp_paths):
        pytest.importorskip("orbax.checkpoint")
        from eegnetreplication_tpu.predict import load_model_from_checkpoint

        loader = make_loader(n_trials=24, n_channels=4, n_times=64)
        within_subject_training(
            epochs=2, config=CFG, loader=loader, subjects=(1,),
            paths=tmp_paths, seed=0, ckpt_format="orbax")
        orbax_dir = tmp_paths.models / "subject_01_best_model.orbax"
        assert orbax_dir.is_dir()
        assert not (tmp_paths.models / "subject_01_best_model.npz").exists()
        model, params, _ = load_model_from_checkpoint(orbax_dir)
        assert (model.n_channels, model.n_times) == (4, 64)

    def test_unknown_format_rejected(self, tmp_paths):
        loader = make_loader(n_trials=24, n_channels=4, n_times=64)
        with pytest.raises(ValueError, match="ckpt_format"):
            within_subject_training(
                epochs=2, config=CFG, loader=loader, subjects=(1,),
                paths=tmp_paths, seed=0, ckpt_format="hdf5")


class TestAutoChunking:
    """checkpoint_every=None auto-chunks long runs (XLA long-scan compile
    cliff, BENCH_NOTES.md); short runs and explicit 0 stay single-program."""

    def _run(self, tmp_paths, epochs, **kw):
        loader = make_loader(n_trials=24, n_channels=4, n_times=64)
        return within_subject_training(
            epochs=epochs, config=CFG, loader=loader, subjects=(1,),
            paths=tmp_paths, seed=0, save_models=False, **kw)

    def test_long_run_auto_chunks(self, tmp_paths):
        # The crash hook only fires inside the chunked loop: raising proves
        # the auto default picked chunked segments.
        with pytest.raises(RuntimeError, match="injected crash"):
            self._run(tmp_paths, epochs=120, _crash_after_chunk=1)
        assert (tmp_paths.models / "within_subject_eegnet.run.npz").exists()

    def test_short_run_stays_fused(self, tmp_paths):
        result = self._run(tmp_paths, epochs=4, _crash_after_chunk=1)
        assert np.isfinite(result.avg_test_acc)  # hook never fired

    def test_explicit_zero_forces_single_program(self, tmp_paths):
        result = self._run(tmp_paths, epochs=120, checkpoint_every=0,
                           _crash_after_chunk=1)
        assert np.isfinite(result.avg_test_acc)  # hook never fired

    def test_resume_needs_chunked_run(self, tmp_paths):
        with pytest.raises(ValueError, match="chunked run"):
            self._run(tmp_paths, epochs=4, resume=True)

    @pytest.mark.slow
    def test_auto_chunked_resume_completes(self, tmp_paths):
        uninterrupted = self._run(tmp_paths, epochs=120)
        with pytest.raises(RuntimeError, match="injected crash"):
            self._run(tmp_paths, epochs=120, _crash_after_chunk=1)
        resumed = self._run(tmp_paths, epochs=120, resume=True)
        np.testing.assert_array_equal(resumed.fold_test_acc,
                                      uninterrupted.fold_test_acc)

    def test_auto_chunk_size_prefers_divisors(self):
        from eegnetreplication_tpu.training.protocols import _auto_chunk_size

        assert _auto_chunk_size(500) == 50   # exact divisor at the target
        assert _auto_chunk_size(120) == 40   # nearest divisor to 50
        assert _auto_chunk_size(150) == 50
        assert _auto_chunk_size(104) == 52
        assert _auto_chunk_size(127) == 50   # prime: fallback + remainder


class TestFoldBatching:
    """fold_batch groups folds into separate compiled programs; results must
    be bit-identical to the single-program run (global init/key derivation)."""

    def _run(self, tmp_paths, **kw):
        loader = make_loader(n_trials=32, n_channels=4, n_times=64)
        return within_subject_training(
            epochs=4, config=CFG, loader=loader, subjects=(1, 2),
            paths=tmp_paths, seed=0, save_models=False, **kw)

    @pytest.mark.slow
    def test_batched_matches_single_program(self, tmp_paths, caplog):
        import logging

        import jax

        whole = self._run(tmp_paths)                 # 8 folds, one program
        with caplog.at_level(logging.INFO):
            batched = self._run(tmp_paths, fold_batch=3)  # groups of 3+3+2
        # Grouping must be scientifically transparent: same fold accuracies
        # and same trajectories to f32 rounding.  Bitwise equality is NOT
        # the contract across groupings — an 8-fold and a 3-fold batched
        # dot_general may tile reductions differently (seen with the
        # banded conv schedule); resume within one grouping stays bitwise
        # (test_batched_chunked_crash_resume).
        np.testing.assert_allclose(batched.fold_test_acc,
                                   whole.fold_test_acc, atol=1e-3)
        # grouped runs log per-group lines AND a protocol-level aggregate
        lines = [r.getMessage() for r in caplog.records
                 if r.getMessage().startswith("Throughput: ")]
        assert any("groups" in line for line in lines), lines
        assert batched.fold_epochs_trained == len(batched.fold_test_acc) * 4
        for a, b in zip(batched.best_states, whole.best_states):
            for la, lb in zip(jax.tree_util.tree_leaves(a),
                              jax.tree_util.tree_leaves(b)):
                # atol: reduction-order noise (~1e-7/step) amplified by 4
                # epochs of Adam+BN; near-zero params make rtol meaningless.
                np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                           atol=5e-4, rtol=5e-2)

    @pytest.mark.slow
    def test_batched_chunked_crash_resume(self, tmp_paths):
        uninterrupted = self._run(tmp_paths, fold_batch=3, checkpoint_every=2)
        with pytest.raises(RuntimeError, match="injected crash"):
            self._run(tmp_paths, fold_batch=3, checkpoint_every=2,
                      _crash_after_chunk=1)
        # group-0 snapshot survives the crash for resume
        assert (tmp_paths.models
                / "within_subject_eegnet.run.npz.g0").exists()
        resumed = self._run(tmp_paths, fold_batch=3, checkpoint_every=2,
                            resume=True)
        np.testing.assert_array_equal(resumed.fold_test_acc,
                                      uninterrupted.fold_test_acc)
        # completion cleans up every group snapshot
        assert not list(tmp_paths.models.glob("*.run.npz.g*"))

    def test_invalid_fold_batch_rejected(self, tmp_paths):
        with pytest.raises(ValueError, match="fold_batch"):
            self._run(tmp_paths, fold_batch=-1)

    @pytest.mark.slow
    def test_device_fault_halves_group_and_completes(self, tmp_paths,
                                                     caplog, monkeypatch):
        """An accelerator fault on a too-large group halves the group size
        and continues instead of dying hours into a protocol (VERDICT r4
        weak #4): 8 folds at fold_batch=6 faults (>2), halves to 3, faults
        again, halves to 1, completes all 8 folds — and records the
        working size for this device_kind."""
        import logging

        from eegnetreplication_tpu.training import protocols as P

        limit_file = tmp_paths.project_root / "fold_batch_limits.json"
        monkeypatch.setattr(P, "_fold_batch_limit_path", lambda: limit_file)
        whole = self._run(tmp_paths)                 # 8 folds, one program
        with caplog.at_level(logging.WARNING):
            halved = self._run(tmp_paths, fold_batch=6,
                               _fault_if_folds_over=2)
        assert any("halving the fold group" in r.getMessage()
                   for r in caplog.records)
        assert halved.fold_test_acc.shape == whole.fold_test_acc.shape
        np.testing.assert_allclose(halved.fold_test_acc,
                                   whole.fold_test_acc, atol=1e-3)
        # Only the size that actually COMPLETED a group is recorded.
        recorded = json.loads(limit_file.read_text())
        assert [v["limit"] for v in recorded.values()] == [1]

    def test_genuine_error_not_swallowed_by_halving(self, tmp_paths):
        """The halving retry is for accelerator faults only: a Python-level
        crash inside a group (the injected-chunk RuntimeError) must
        propagate, not silently shrink the group."""
        with pytest.raises(RuntimeError, match="injected crash"):
            self._run(tmp_paths, fold_batch=3, checkpoint_every=2,
                      _crash_after_chunk=1)

    @pytest.mark.slow
    def test_resume_across_group_size_change(self, tmp_paths, caplog):
        """A group snapshot from a DIFFERENT fold_batch (e.g. the old
        45-fold default crashed, the retry auto-resolves to 15) must retrain
        that group fresh with a warning — not hard-fail the signature
        check — and completion must clear the foreign .g* files."""
        import logging

        with pytest.raises(RuntimeError, match="injected crash"):
            self._run(tmp_paths, fold_batch=4, checkpoint_every=2,
                      _crash_after_chunk=1)
        assert (tmp_paths.models
                / "within_subject_eegnet.run.npz.g0").exists()
        with caplog.at_level(logging.WARNING):
            resumed = self._run(tmp_paths, fold_batch=3, checkpoint_every=2,
                                resume=True)
        assert any("different fold grouping" in r.getMessage()
                   for r in caplog.records)
        assert not list(tmp_paths.models.glob("*.run.npz.g*"))
        uninterrupted = self._run(tmp_paths, fold_batch=3, checkpoint_every=2)
        np.testing.assert_array_equal(resumed.fold_test_acc,
                                      uninterrupted.fold_test_acc)

    @pytest.mark.slow
    def test_resume_with_corrupt_group_snapshot(self, tmp_paths, caplog):
        """An existing-but-unreadable group snapshot degrades to a fresh
        retrain with a warning, not a loader crash."""
        import logging

        with pytest.raises(RuntimeError, match="injected crash"):
            self._run(tmp_paths, fold_batch=3, checkpoint_every=2,
                      _crash_after_chunk=1)
        g0 = tmp_paths.models / "within_subject_eegnet.run.npz.g0"
        assert g0.exists()
        g0.write_bytes(b"not a zip archive")
        with caplog.at_level(logging.WARNING):
            resumed = self._run(tmp_paths, fold_batch=3, checkpoint_every=2,
                                resume=True)
        assert any("unreadable" in r.getMessage() for r in caplog.records)
        whole = self._run(tmp_paths, fold_batch=3, checkpoint_every=2)
        np.testing.assert_array_equal(resumed.fold_test_acc,
                                      whole.fold_test_acc)

    @pytest.mark.slow
    def test_resume_across_batching_warns_and_cleans(self, tmp_paths, caplog):
        """A crashed UNBATCHED run's snapshot cannot seed a grouped retry
        (e.g. auto fold-batching kicked in on the rerun): the run must say
        it is restarting, and completion must clear the stale snapshot."""
        import logging

        with pytest.raises(RuntimeError, match="injected crash"):
            self._run(tmp_paths, checkpoint_every=2, _crash_after_chunk=1)
        snap = tmp_paths.models / "within_subject_eegnet.run.npz"
        assert snap.exists()
        with caplog.at_level(logging.WARNING):
            resumed = self._run(tmp_paths, fold_batch=3, checkpoint_every=2,
                                resume=True)
        assert any("ungrouped run snapshot" in r.getMessage()
                   for r in caplog.records)
        assert not snap.exists()  # grouped completion clears the stale file
        uninterrupted = self._run(tmp_paths, fold_batch=3, checkpoint_every=2)
        np.testing.assert_array_equal(resumed.fold_test_acc,
                                      uninterrupted.fold_test_acc)

    def test_zero_opts_out_of_batching(self, tmp_paths):
        # 0 = "one fused program" (mirrors checkpoint_every=0); identical
        # to the unbatched run.
        whole = self._run(tmp_paths)
        explicit = self._run(tmp_paths, fold_batch=0)
        np.testing.assert_array_equal(explicit.fold_test_acc,
                                      whole.fold_test_acc)

    def test_effective_fold_batch_mirrors_grouping(self):
        """ProtocolResult.fold_batch must record what _run_folds actually
        did, so the resolver mirrors its grouping condition exactly."""
        from eegnetreplication_tpu.training.protocols import (
            _effective_fold_batch,
        )

        assert _effective_fold_batch(15, None, 90) == 15
        assert _effective_fold_batch(None, None, 90) is None
        assert _effective_fold_batch(0, None, 90) is None
        assert _effective_fold_batch(100, None, 90) is None  # one program
        assert _effective_fold_batch(90, None, 90) is None   # one program
        assert _effective_fold_batch(15, object(), 90) is None  # mesh

    def test_read_snapshot_signature_robust(self, tmp_path):
        from eegnetreplication_tpu.training.checkpoint import (
            read_snapshot_signature,
        )

        assert read_snapshot_signature(tmp_path / "missing.npz") is None
        bad = tmp_path / "bad.npz"
        bad.write_bytes(b"not a zip")
        assert read_snapshot_signature(bad) is None
        unsigned = tmp_path / "unsigned.npz"
        np.savez(unsigned, x=np.zeros(3))
        assert read_snapshot_signature(unsigned) is None

    def test_cs_auto_fold_batch_on_accelerator(self, monkeypatch, caplog):
        """CS runs on a non-CPU backend default to CS_ACCEL_FOLD_BATCH-fold
        groups (measured v5e limit: 30+-fold CS programs fault the device);
        CPU, meshes, explicit values and 0 leave the choice alone."""
        import logging

        import jax

        from eegnetreplication_tpu.training import protocols as P

        monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
        assert P._cs_auto_fold_batch(90, None, None) is None  # cpu backend
        assert P._cs_auto_fold_batch(90, None, 45) == 45
        assert P._cs_auto_fold_batch(90, None, 0) is None
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        with caplog.at_level(logging.INFO):
            assert (P._cs_auto_fold_batch(90, None, None)
                    == P.CS_ACCEL_FOLD_BATCH)
        assert any("Auto fold batching" in r.getMessage()
                   for r in caplog.records)
        assert P._cs_auto_fold_batch(P.CS_ACCEL_FOLD_BATCH, None, None) is None
        assert P._cs_auto_fold_batch(90, object(), None) is None  # mesh
        assert P._cs_auto_fold_batch(90, None, 45) == 45

    def test_ungrouped_completion_clears_stale_group_snapshots(self, tmp_paths):
        with pytest.raises(RuntimeError, match="injected crash"):
            self._run(tmp_paths, fold_batch=3, checkpoint_every=2,
                      _crash_after_chunk=1)
        assert list(tmp_paths.models.glob("*.run.npz.g*"))
        self._run(tmp_paths, checkpoint_every=2)  # complete without batching
        assert not list(tmp_paths.models.glob("*.run.npz.g*"))
