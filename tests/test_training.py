"""Tests for the training engine: steps, max-norm modes, fused fold loop.

Extends the reference's integration tests (one optimizer step with NaN/Inf
checks, ``tests/test_model.py:236-280``) with what the reference lacks:
deterministic-seed regression, learnability on a separable synthetic task,
masked-padding invariants, and vmap-over-folds equivalence.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from eegnetreplication_tpu.models import EEGNet
from eegnetreplication_tpu.training import (
    FoldSpec,
    TrainState,
    init_fold_states,
    make_fold_spec,
    make_fold_trainer,
    make_optimizer,
    train_step,
)
from eegnetreplication_tpu.training.steps import (
    clamp_reference_maxnorm,
    project_paper_maxnorm,
    weighted_cross_entropy,
)

C, T = 8, 64


def small_model(p=0.5):
    return EEGNet(n_channels=C, n_times=T, dropout_rate=p)


def make_state(model, tx, seed=0):
    variables = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, C, T)),
                           train=False)
    return TrainState.create(variables, tx)


def separable_pool(n_per_class=40, seed=0):
    """Synthetic 4-class pool where class k has a sinusoid at distinct freq."""
    rng = np.random.RandomState(seed)
    xs, ys = [], []
    t = np.arange(T) / 64.0
    for k in range(4):
        freq = 4.0 + 4.0 * k
        sig = np.sin(2 * np.pi * freq * t)
        x = rng.randn(n_per_class, C, T) * 0.3 + sig[None, None, :]
        xs.append(x)
        ys.append(np.full(n_per_class, k))
    X = np.concatenate(xs).astype(np.float32)
    y = np.concatenate(ys).astype(np.int32)
    perm = rng.permutation(len(y))
    return jnp.asarray(X[perm]), jnp.asarray(y[perm])


class TestSteps:
    def test_one_step_finite_and_changes_params(self):
        model, tx = small_model(), make_optimizer()
        state = make_state(model, tx)
        x = jax.random.normal(jax.random.PRNGKey(1), (16, C, T))
        y = jnp.arange(16) % 4
        w = jnp.ones(16)
        new_state, loss = train_step(model, tx, state, x, y, w,
                                     jax.random.PRNGKey(2))
        assert np.isfinite(float(loss))
        diffs = jax.tree_util.tree_map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))),
            new_state.params, state.params)
        assert max(jax.tree_util.tree_leaves(diffs)) > 0

    def test_empty_batch_is_noop(self):
        model, tx = small_model(), make_optimizer()
        state = make_state(model, tx)
        x = jax.random.normal(jax.random.PRNGKey(1), (8, C, T))
        y = jnp.zeros(8, jnp.int32)
        w = jnp.zeros(8)
        new_state, loss = train_step(model, tx, state, x, y, w,
                                     jax.random.PRNGKey(2))
        assert float(loss) == 0.0
        for a, b in zip(jax.tree_util.tree_leaves(new_state.params),
                        jax.tree_util.tree_leaves(state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(new_state.opt_state),
                        jax.tree_util.tree_leaves(state.opt_state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_weighted_ce_ignores_padding(self):
        logits = jnp.asarray(np.random.RandomState(0).randn(6, 4), jnp.float32)
        y = jnp.asarray([0, 1, 2, 3, 0, 1])
        full = weighted_cross_entropy(logits[:4], y[:4], jnp.ones(4))
        padded = weighted_cross_entropy(
            logits, y, jnp.asarray([1, 1, 1, 1, 0, 0], jnp.float32))
        np.testing.assert_allclose(float(full), float(padded), rtol=1e-6)

    def test_reference_maxnorm_clamps_only_targets(self):
        model, tx = small_model(), make_optimizer()
        state = make_state(model, tx)
        big = jax.tree_util.tree_map(lambda p: jnp.full_like(p, 5.0),
                                     state.params)
        clamped = clamp_reference_maxnorm(big)
        assert float(jnp.max(clamped["spatial_conv"]["kernel"])) == 1.0
        assert float(jnp.max(clamped["classifier"]["kernel"])) == 0.25
        assert float(jnp.max(clamped["classifier"]["bias"])) == 5.0
        assert float(jnp.max(clamped["temporal_conv"]["kernel"])) == 5.0

    def test_paper_maxnorm_projects_norms(self):
        model, tx = small_model(), make_optimizer()
        state = make_state(model, tx)
        big = jax.tree_util.tree_map(lambda p: jnp.full_like(p, 5.0),
                                     state.params)
        proj = project_paper_maxnorm(big)
        sp = np.asarray(proj["spatial_conv"]["kernel"])
        norms = np.sqrt((sp ** 2).sum(axis=(0, 1, 2)))
        assert np.all(norms <= 1.0 + 1e-5)
        cl = np.asarray(proj["classifier"]["kernel"])
        assert np.all(np.sqrt((cl ** 2).sum(axis=0)) <= 0.25 + 1e-5)
        np.testing.assert_allclose(np.asarray(proj["temporal_conv"]["kernel"]),
                                   5.0)


class TestFoldTrainer:
    def make_setup(self, epochs=5, batch_size=32, maxnorm_mode="reference"):
        model = small_model()
        tx = make_optimizer()
        pool_x, pool_y = separable_pool()
        n = len(pool_y)  # 160
        idx = np.arange(n)
        spec = make_fold_spec(idx[:96], idx[96:128], idx[128:],
                              train_pad=96, val_pad=32, test_pad=32)
        trainer = make_fold_trainer(
            model, tx, batch_size=batch_size, epochs=epochs, train_pad=96,
            val_pad=32, test_pad=32, maxnorm_mode=maxnorm_mode)
        state = make_state(model, tx)
        return trainer, pool_x, pool_y, spec, state

    def test_learns_separable_task(self):
        trainer, pool_x, pool_y, spec, state = self.make_setup(epochs=30)
        result = jax.jit(trainer)(pool_x, pool_y, spec, state,
                                  jax.random.PRNGKey(0))
        assert result.train_losses.shape == (30,)
        assert float(result.train_losses[-1]) < float(result.train_losses[0])
        assert float(result.best_val_acc) > 60.0
        assert float(result.test_accuracy) > 60.0

    def test_deterministic_given_seed(self):
        trainer, pool_x, pool_y, spec, state = self.make_setup(epochs=3)
        r1 = jax.jit(trainer)(pool_x, pool_y, spec, state, jax.random.PRNGKey(7))
        r2 = jax.jit(trainer)(pool_x, pool_y, spec, state, jax.random.PRNGKey(7))
        np.testing.assert_array_equal(np.asarray(r1.val_accuracies),
                                      np.asarray(r2.val_accuracies))
        np.testing.assert_array_equal(np.asarray(r1.test_accuracy),
                                      np.asarray(r2.test_accuracy))

    def test_best_tracking_matches_max(self):
        trainer, pool_x, pool_y, spec, state = self.make_setup(epochs=10)
        r = jax.jit(trainer)(pool_x, pool_y, spec, state, jax.random.PRNGKey(1))
        np.testing.assert_allclose(float(r.best_val_acc),
                                   float(np.max(np.asarray(r.val_accuracies))),
                                   rtol=1e-6)
        np.testing.assert_allclose(float(r.min_val_loss),
                                   float(np.min(np.asarray(r.val_losses))),
                                   rtol=1e-6)

    @pytest.mark.slow
    def test_padded_fold_equivalent_to_exact_fold(self):
        """Padding the index arrays must not change the math."""
        model = small_model(p=0.0)  # no dropout so runs are comparable
        tx = make_optimizer()
        pool_x, pool_y = separable_pool()
        idx = np.arange(160)
        state = make_state(model, tx)
        key = jax.random.PRNGKey(3)

        exact_spec = make_fold_spec(idx[:96], idx[96:128], idx[128:160],
                                    train_pad=96, val_pad=32, test_pad=32)
        exact = make_fold_trainer(model, tx, batch_size=32, epochs=3,
                                  train_pad=96, val_pad=32, test_pad=32)
        r_exact = jax.jit(exact)(pool_x, pool_y, exact_spec, state, key)

        padded_spec = make_fold_spec(idx[:96], idx[96:128], idx[128:160],
                                     train_pad=128, val_pad=64, test_pad=64)
        padded = make_fold_trainer(model, tx, batch_size=32, epochs=3,
                                   train_pad=128, val_pad=64, test_pad=64)
        r_padded = jax.jit(padded)(pool_x, pool_y, padded_spec, state, key)

        # Val/test metrics must agree exactly in exact arithmetic; allow f32
        # reduction-order noise.
        np.testing.assert_allclose(np.asarray(r_exact.val_accuracies),
                                   np.asarray(r_padded.val_accuracies),
                                   atol=1e-3)
        np.testing.assert_allclose(float(r_exact.test_accuracy),
                                   float(r_padded.test_accuracy), atol=1e-3)

    def test_vmap_over_folds_matches_single(self):
        model = small_model(p=0.0)
        tx = make_optimizer()
        pool_x, pool_y = separable_pool()
        idx = np.arange(160)
        trainer = make_fold_trainer(model, tx, batch_size=32, epochs=2,
                                    train_pad=96, val_pad=32, test_pad=32)
        spec_a = make_fold_spec(idx[:96], idx[96:128], idx[128:],
                                train_pad=96, val_pad=32, test_pad=32)
        spec_b = make_fold_spec(idx[64:160], idx[:32], idx[32:64],
                                train_pad=96, val_pad=32, test_pad=32)
        states = init_fold_states(model, tx, 2, (C, T), seed=0)
        keys = jax.random.split(jax.random.PRNGKey(5), 2)

        specs = jax.tree_util.tree_map(
            lambda a, b: jnp.stack([a, b]), spec_a, spec_b)
        vr = jax.jit(jax.vmap(trainer, in_axes=(None, None, 0, 0, 0)))(
            pool_x, pool_y, specs, states, keys)

        state_a = jax.tree_util.tree_map(lambda x: x[0], states)
        ra = jax.jit(trainer)(pool_x, pool_y, spec_a, state_a, keys[0])
        np.testing.assert_allclose(np.asarray(vr.val_accuracies[0]),
                                   np.asarray(ra.val_accuracies), atol=1e-3)
        np.testing.assert_allclose(float(vr.test_accuracy[0]),
                                   float(ra.test_accuracy), atol=1e-3)
