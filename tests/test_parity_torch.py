"""Golden-value parity: Flax EEGNet vs an independent PyTorch EEGNet.

The reference has no cross-framework parity tests; SURVEY.md §4 calls for
them.  A PyTorch EEGNet is built here from the published architecture
(Lawhern et al. 2018; reference layer spec at ``model.py:22-84``), the Flax
parameters are transplanted into it, and eval-mode forward passes are
compared.  This pins down padding semantics, BN eps, ELU, pooling and the
NHWC-vs-NCHW flatten permutation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402

from eegnetreplication_tpu.models import EEGNet  # noqa: E402


def build_torch_eegnet(C=22, T=257, F1=8, D=2, p=0.5):
    """Independent torch EEGNet matching the published architecture."""
    F2 = F1 * D

    class TorchEEGNet(tnn.Module):
        def __init__(self):
            super().__init__()
            self.temporal = tnn.Sequential(
                tnn.Conv2d(1, F1, (1, 32), padding="same", bias=False),
                tnn.BatchNorm2d(F1),
            )
            self.spatial = tnn.Conv2d(F1, D * F1, (C, 1), padding="valid",
                                      groups=F1, bias=False)
            self.aggregation = tnn.Sequential(
                tnn.BatchNorm2d(D * F1), tnn.ELU(), tnn.AvgPool2d((1, 4)),
                tnn.Dropout(p),
            )
            self.block_2 = tnn.Sequential(
                tnn.Conv2d(D * F1, D * F1, (1, 16), padding="same",
                           groups=D * F1, bias=False),
                tnn.Conv2d(D * F1, F2, (1, 1), padding="same", bias=False),
                tnn.BatchNorm2d(F2), tnn.ELU(), tnn.AvgPool2d((1, 8)),
                tnn.Dropout(p), tnn.Flatten(),
            )
            self.classifier = tnn.Linear(F2 * (T // 32), 4, bias=True)

        def forward(self, x):
            x = torch.unsqueeze(x, 1)
            x = self.temporal(x)
            x = self.spatial(x)
            x = self.aggregation(x)
            x = self.block_2(x)
            return self.classifier(x)

    return TorchEEGNet()


def transplant_flax_to_torch(variables, tmodel, F2, t_prime):
    """Copy flax params/batch_stats into the torch model in-place."""
    p = jax.tree_util.tree_map(np.asarray, variables["params"])
    bs = jax.tree_util.tree_map(np.asarray, variables["batch_stats"])

    def conv_w(kernel):  # (kh, kw, in/g, out) -> (out, in/g, kh, kw)
        return torch.tensor(np.transpose(kernel, (3, 2, 0, 1)))

    sd = tmodel.state_dict()
    sd["temporal.0.weight"] = conv_w(p["temporal_conv"]["kernel"])
    sd["temporal.1.weight"] = torch.tensor(p["temporal_bn"]["scale"])
    sd["temporal.1.bias"] = torch.tensor(p["temporal_bn"]["bias"])
    sd["temporal.1.running_mean"] = torch.tensor(bs["temporal_bn"]["mean"])
    sd["temporal.1.running_var"] = torch.tensor(bs["temporal_bn"]["var"])
    sd["spatial.weight"] = conv_w(p["spatial_conv"]["kernel"])
    sd["aggregation.0.weight"] = torch.tensor(p["spatial_bn"]["scale"])
    sd["aggregation.0.bias"] = torch.tensor(p["spatial_bn"]["bias"])
    sd["aggregation.0.running_mean"] = torch.tensor(bs["spatial_bn"]["mean"])
    sd["aggregation.0.running_var"] = torch.tensor(bs["spatial_bn"]["var"])
    sd["block_2.0.weight"] = conv_w(p["separable_depthwise"]["kernel"])
    sd["block_2.1.weight"] = conv_w(p["separable_pointwise"]["kernel"])
    sd["block_2.2.weight"] = torch.tensor(p["block2_bn"]["scale"])
    sd["block_2.2.bias"] = torch.tensor(p["block2_bn"]["bias"])
    sd["block_2.2.running_mean"] = torch.tensor(bs["block2_bn"]["mean"])
    sd["block_2.2.running_var"] = torch.tensor(bs["block2_bn"]["var"])

    # Flax flattens NHWC (1, T', F2) -> index w*F2 + f; torch flattens NCHW
    # (F2, 1, T') -> index f*T' + w.  Permute the classifier input features.
    k = p["classifier"]["kernel"]  # (T'*F2, 4) in flax order
    k_torch = np.zeros((4, F2 * t_prime), dtype=k.dtype)
    for f in range(F2):
        for w in range(t_prime):
            k_torch[:, f * t_prime + w] = k[w * F2 + f, :]
    sd["classifier.weight"] = torch.tensor(k_torch)
    sd["classifier.bias"] = torch.tensor(p["classifier"]["bias"])
    tmodel.load_state_dict(sd)
    tmodel.eval()


@pytest.mark.parametrize("C,T", [(22, 257), (22, 256)])
def test_eval_forward_parity(C, T):
    model = EEGNet(n_channels=C, n_times=T)
    x = np.random.RandomState(0).randn(6, C, T).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x), train=False)

    tmodel = build_torch_eegnet(C=C, T=T)
    transplant_flax_to_torch(variables, tmodel, F2=16, t_prime=T // 32)

    flax_out = np.asarray(model.apply(variables, jnp.asarray(x), train=False))
    with torch.no_grad():
        torch_out = tmodel(torch.tensor(x)).numpy()

    np.testing.assert_allclose(flax_out, torch_out, rtol=1e-4, atol=1e-5)


def _torch_train_steps(tmodel, x, y, batches, mode, limits):
    """Reference-semantics torch loop: Adam(1e-3, eps=1e-7) + CE, with the
    reference's gradient clamp (``model.py:43-44,83-84``) or the paper's true
    max-norm projection applied per step.  Returns per-step losses."""
    opt = torch.optim.Adam(tmodel.parameters(), lr=1e-3, eps=1e-7)
    loss_fn = tnn.CrossEntropyLoss()
    xt, yt = torch.tensor(x), torch.tensor(y.astype(np.int64))
    tmodel.train()
    losses = []
    for idx in batches:
        opt.zero_grad()
        loss = loss_fn(tmodel(xt[idx]), yt[idx])
        loss.backward()
        if mode == "reference":
            for w, lim in limits:
                w.grad.clamp_(-lim, lim)
        opt.step()
        if mode == "paper":
            with torch.no_grad():
                for w, lim in limits:
                    dims = tuple(range(1, w.ndim))  # per-output-filter norm
                    norms = w.pow(2).sum(dim=dims, keepdim=True).sqrt()
                    w.mul_(torch.clamp(lim / norms.clamp_min(1e-12), max=1.0))
        losses.append(float(loss.detach()))
    return losses


@pytest.mark.parametrize("mode", ["reference", "paper"])
def test_training_trajectory_parity(mode):
    """N jitted train_steps track an independent torch Adam+BN loop.

    Same pool, same transplanted init, same batch order, dropout off
    (p=0 keeps train-mode BN active while removing the only stochastic
    element) — the cheapest faithful proxy for full-protocol accuracy
    parity vs the reference's loop (``model.py:130-148``) in a
    network-blocked environment.  Covers both max-norm treatments
    (quirk Q1): the reference's gradient clamp and the paper's weight
    projection.
    """
    from eegnetreplication_tpu.training.checkpoint import from_torch_state_dict
    from eegnetreplication_tpu.training.steps import (
        TrainState,
        make_optimizer,
        train_step,
    )

    C, T, B, n_steps = 22, 257, 32, 60
    rng = np.random.RandomState(3)
    pool_x = rng.randn(160, C, T).astype(np.float32)
    pool_y = rng.randint(0, 4, 160).astype(np.int32)
    batches = []
    while len(batches) < n_steps:
        order = rng.permutation(len(pool_x))
        batches += [order[s:s + B] for s in range(0, len(order), B)]
    batches = batches[:n_steps]

    model = EEGNet(n_channels=C, n_times=T, dropout_rate=0.0)
    variables = model.init(jax.random.PRNGKey(7),
                           jnp.zeros((1, C, T), jnp.float32), train=False)
    tmodel = build_torch_eegnet(C=C, T=T, p=0.0)
    transplant_flax_to_torch(variables, tmodel, F2=16, t_prime=T // 32)

    torch_losses = _torch_train_steps(
        tmodel, pool_x, pool_y, batches, mode,
        limits=[(tmodel.spatial.weight, 1.0),
                (tmodel.classifier.weight, 0.25)])

    tx = make_optimizer()
    state = TrainState.create(variables, tx)
    step = jax.jit(lambda s, bx, by, key: train_step(
        model, tx, s, bx, by, jnp.ones(bx.shape[0]), key,
        maxnorm_mode=mode))
    jax_losses = []
    w_ones = jax.random.PRNGKey(0)  # dropout rng unused at p=0
    for idx in batches:
        state, loss = step(state, jnp.asarray(pool_x[idx]),
                           jnp.asarray(pool_y[idx]), w_ones)
        jax_losses.append(float(loss))

    # Per-step losses must track within float32 drift over 60 steps.
    np.testing.assert_allclose(jax_losses, torch_losses, rtol=2e-3, atol=2e-3)

    # Final parameters must agree once mapped into the flax layout.
    # Exception: temporal_bn's affine params have mathematically ZERO
    # gradient (any per-channel affine shift after temporal_bn is exactly
    # cancelled by spatial_bn's normalization), so their "gradients" are
    # float32 noise ~1e-7 that Adam amplifies to O(lr) random walks which
    # differ between frameworks; bound those by the walk, not by parity.
    t_params, t_bs = from_torch_state_dict(tmodel.state_dict(), f2=16,
                                           t_prime=T // 32)
    j_params = jax.tree_util.tree_map(np.asarray, state.params)
    noise_walk_bound = 1e-3 * n_steps  # lr * n_steps
    for layer, leaves in t_params.items():
        for leaf, tv in leaves.items():
            jv = j_params[layer][leaf]
            if layer == "temporal_bn":
                assert np.max(np.abs(jv - tv)) < noise_walk_bound, (
                    f"{layer}.{leaf} exceeded the Adam noise-walk bound")
                continue
            np.testing.assert_allclose(
                jv, tv, rtol=5e-3, atol=5e-4,
                err_msg=f"{layer}.{leaf} diverged after {n_steps} steps "
                        f"(mode={mode})")
    # BN running stats: torch uses the unbiased batch var for the running
    # update, flax the biased one — allow that n/(n-1) factor.  The atol
    # additionally absorbs the temporal_bn noise walk leaking into the
    # downstream layers' running means (a ~lr-scale shift of the conv
    # outputs each step).
    j_bs = jax.tree_util.tree_map(np.asarray, state.batch_stats)
    for layer, leaves in t_bs.items():
        for leaf, tv in leaves.items():
            np.testing.assert_allclose(
                j_bs[layer][leaf], tv, rtol=5e-3, atol=2e-2,
                err_msg=f"batch_stats {layer}.{leaf} diverged (mode={mode})")


def _drift_pool(n_train, n_val, C, T, class_sep=1.2, seed=5):
    """Separable pool from the shared synthetic generator, split train/val."""
    from synthetic import synthetic_subject

    ds = synthetic_subject(seed, "Train", n_trials=n_train + n_val,
                           n_channels=C, n_times=T, class_sep=class_sep)
    idx = np.random.RandomState(seed).permutation(len(ds.X))
    return (np.asarray(ds.X, np.float32), np.asarray(ds.y, np.int32),
            idx[:n_train].astype(np.int32), idx[n_train:].astype(np.int32))


def _torch_epoch_loop(tmodel, x, y, tr_idx, va_idx, batch, epochs,
                      order_rng, record_orders=None):
    """Reference epoch loop (``model.py:130-168``): per-epoch shuffle,
    partial last batch (``DataLoader`` default ``drop_last=False``,
    ``train.py:87-89``), reference-mode grad clamp.  Returns per-epoch mean
    train losses and the final eval-mode val accuracy.  ``record_orders``
    captures each epoch's batch index lists so a twin can replay the
    identical order."""
    opt = torch.optim.Adam(tmodel.parameters(), lr=1e-3, eps=1e-7)
    loss_fn = tnn.CrossEntropyLoss()
    xt, yt = torch.tensor(x), torch.tensor(y.astype(np.int64))
    limits = [(tmodel.spatial.weight, 1.0), (tmodel.classifier.weight, 0.25)]
    epoch_losses = []
    for _ in range(epochs):
        order = order_rng.permutation(tr_idx)
        batches = [order[s:s + batch] for s in range(0, len(order), batch)]
        if record_orders is not None:
            record_orders.append(batches)
        tmodel.train()
        running = 0.0
        for idx in batches:
            opt.zero_grad()
            loss = loss_fn(tmodel(xt[idx]), yt[idx])
            loss.backward()
            for w, lim in limits:
                w.grad.clamp_(-lim, lim)
            opt.step()
            running += float(loss.detach())
        epoch_losses.append(running / len(batches))
    tmodel.eval()
    with torch.no_grad():
        pred = tmodel(xt[va_idx]).argmax(1).numpy()
    return (np.asarray(epoch_losses),
            float(100.0 * np.mean(pred == y[va_idx])))


class TestLongHorizonDrift:
    """500-epoch drift bounds (VERDICT r2 item 4 + weak item 5).

    The short trajectory test above certifies per-step numerics; these
    certify the regime the accuracy claim lives in — a full training run —
    where f32 reassociation and BN-stat drift compound chaotically.  The
    honest assertable quantities at that horizon are the ENDPOINT metrics
    (final val accuracy) and the early-horizon loss agreement; per-step
    parity at epoch 500 does not exist for any two frameworks.
    ``EEGTPU_DRIFT_EPOCHS`` scales the horizon (default 500).
    """

    EPOCHS = int(__import__("os").environ.get("EEGTPU_DRIFT_EPOCHS", "500"))
    C, T, B = 8, 64, 16

    def _models(self):
        model = EEGNet(n_channels=self.C, n_times=self.T, F1=4, D=2,
                       dropout_rate=0.0)
        variables = model.init(
            jax.random.PRNGKey(13),
            jnp.zeros((1, self.C, self.T), jnp.float32), train=False)
        tmodel = build_torch_eegnet(C=self.C, T=self.T, F1=4, D=2, p=0.0)
        transplant_flax_to_torch(variables, tmodel, F2=8,
                                 t_prime=self.T // 32)
        return model, variables, tmodel

    @pytest.mark.slow
    def test_identical_order_full_batches(self):
        """Same init, same per-epoch batch order, full batches only:
        isolates pure framework drift (torch loop vs jitted train_step)."""
        from eegnetreplication_tpu.training.steps import (
            TrainState,
            make_optimizer,
            train_step,
        )
        from eegnetreplication_tpu.utils.logging import logger

        # 112 = 7 full batches of 16: no partial batch on either side.
        X, y, tr, va = _drift_pool(112, 32, self.C, self.T)
        model, variables, tmodel = self._models()
        orders: list = []
        t_losses, t_val = _torch_epoch_loop(
            tmodel, X, y, tr, va, self.B, self.EPOCHS,
            np.random.RandomState(21), record_orders=orders)

        tx = make_optimizer()
        state = TrainState.create(variables, tx)
        step = jax.jit(lambda s, bx, by: train_step(
            model, tx, s, bx, by, jnp.ones(bx.shape[0]),
            jax.random.PRNGKey(0)))
        j_losses = []
        for batches in orders:
            running = 0.0
            for idx in batches:
                state, loss = step(state, jnp.asarray(X[idx]),
                                   jnp.asarray(y[idx]))
                running += float(loss)
            j_losses.append(running / len(batches))
        j_losses = np.asarray(j_losses)
        logits = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            jnp.asarray(X[va]), train=False)
        j_val = float(100.0 * np.mean(
            np.asarray(jnp.argmax(logits, -1)) == y[va]))

        # Loss-divergence curve, recorded at the reference log cadence.
        div = np.abs(j_losses - t_losses)
        for e in range(1, self.EPOCHS + 1):
            if e == 1 or e % 50 == 0 or e == self.EPOCHS:
                logger.info(
                    "drift(identical-order) epoch %d/%d: |jax-torch| "
                    "train-loss delta %.2e (torch %.4f, jax %.4f)",
                    e, self.EPOCHS, div[e - 1], t_losses[e - 1],
                    j_losses[e - 1])
        # Early horizon: trajectories must still be numerically locked.
        assert float(np.mean(div[:20])) < 5e-3, div[:20]
        # Endpoint: both converge on this separable task; the final val
        # accuracies must agree within a stated tolerance.
        logger.info("drift(identical-order) final val acc: torch %.2f%% "
                    "jax %.2f%%", t_val, j_val)
        if self.EPOCHS >= 100:  # scaled-down horizons skip the convergence
            assert t_val >= 85.0 and j_val >= 85.0, (t_val, j_val)
        assert abs(t_val - j_val) <= 10.0, (t_val, j_val)

    @pytest.mark.slow
    def test_partial_batch_bn_deviation(self):
        """Product-path deviation measured, not assumed: the fused trainer
        wrap-pads every batch to full size (``loop.py:87-102``) while the
        reference's last partial batch feeds BN fewer samples.  Same init,
        same data, a 500-epoch run each way — the endpoint accuracies must
        agree within the stated tolerance."""
        from eegnetreplication_tpu.training import (
            init_fold_carry,
            make_fold_spec,
            make_multi_fold_segment,
            make_optimizer,
        )
        from eegnetreplication_tpu.training.steps import TrainState
        from eegnetreplication_tpu.utils.logging import logger

        # 116 = 7 full batches + a 4-sample partial batch on the torch side.
        X, y, tr, va = _drift_pool(116, 32, self.C, self.T)
        model, variables, tmodel = self._models()
        t_losses, t_val = _torch_epoch_loop(
            tmodel, X, y, tr, va, self.B, self.EPOCHS,
            np.random.RandomState(33))

        tx = make_optimizer()
        state = TrainState.create(variables, tx)
        states = jax.tree_util.tree_map(lambda l: l[None], state)
        spec = make_fold_spec(tr, va, va, train_pad=len(tr),
                              val_pad=len(va), test_pad=len(va))
        stacked = jax.tree_util.tree_map(lambda l: jnp.asarray(l)[None], spec)
        segment = make_multi_fold_segment(model, tx, batch_size=self.B)
        carry = jax.vmap(init_fold_carry)(states)
        epoch_keys = jax.random.split(
            jax.random.PRNGKey(29), self.EPOCHS)[None]
        px, py = jnp.asarray(X), jnp.asarray(y)
        chunk = 50 if self.EPOCHS % 50 == 0 else self.EPOCHS
        last_val_acc = None
        for lo in range(0, self.EPOCHS, chunk):
            carry, per_epoch = segment(px, py, stacked, carry,
                                       epoch_keys[:, lo:lo + chunk])
            last_val_acc = float(np.asarray(per_epoch[2])[0, -1])
        j_val = last_val_acc

        logger.info("drift(partial-batch BN) final val acc: torch(partial) "
                    "%.2f%% jax(wrap-padded) %.2f%%", t_val, j_val)
        if self.EPOCHS >= 100:  # scaled-down horizons skip the convergence
            assert t_val >= 85.0 and j_val >= 85.0, (t_val, j_val)
        assert abs(t_val - j_val) <= 10.0, (t_val, j_val)


def test_parity_with_perturbed_bn_stats():
    """Parity must hold with non-trivial running stats, not just init."""
    model = EEGNet()
    x = np.random.RandomState(1).randn(4, 22, 257).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(1), jnp.asarray(x), train=False)

    # Run a few train-mode passes so running stats move off (0, 1).
    vars_mut = variables
    for seed in range(3):
        _, updates = model.apply(
            vars_mut, jnp.asarray(x), train=True,
            rngs={"dropout": jax.random.PRNGKey(seed)}, mutable=["batch_stats"],
        )
        vars_mut = {"params": vars_mut["params"],
                    "batch_stats": updates["batch_stats"]}

    tmodel = build_torch_eegnet()
    transplant_flax_to_torch(vars_mut, tmodel, F2=16, t_prime=8)

    flax_out = np.asarray(model.apply(vars_mut, jnp.asarray(x), train=False))
    with torch.no_grad():
        torch_out = tmodel(torch.tensor(x)).numpy()
    np.testing.assert_allclose(flax_out, torch_out, rtol=1e-4, atol=1e-5)
