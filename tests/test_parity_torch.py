"""Golden-value parity: Flax EEGNet vs an independent PyTorch EEGNet.

The reference has no cross-framework parity tests; SURVEY.md §4 calls for
them.  A PyTorch EEGNet is built here from the published architecture
(Lawhern et al. 2018; reference layer spec at ``model.py:22-84``), the Flax
parameters are transplanted into it, and eval-mode forward passes are
compared.  This pins down padding semantics, BN eps, ELU, pooling and the
NHWC-vs-NCHW flatten permutation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402

from eegnetreplication_tpu.models import EEGNet  # noqa: E402


def build_torch_eegnet(C=22, T=257, F1=8, D=2, p=0.5):
    """Independent torch EEGNet matching the published architecture."""
    F2 = F1 * D

    class TorchEEGNet(tnn.Module):
        def __init__(self):
            super().__init__()
            self.temporal = tnn.Sequential(
                tnn.Conv2d(1, F1, (1, 32), padding="same", bias=False),
                tnn.BatchNorm2d(F1),
            )
            self.spatial = tnn.Conv2d(F1, D * F1, (C, 1), padding="valid",
                                      groups=F1, bias=False)
            self.aggregation = tnn.Sequential(
                tnn.BatchNorm2d(D * F1), tnn.ELU(), tnn.AvgPool2d((1, 4)),
                tnn.Dropout(p),
            )
            self.block_2 = tnn.Sequential(
                tnn.Conv2d(D * F1, D * F1, (1, 16), padding="same",
                           groups=D * F1, bias=False),
                tnn.Conv2d(D * F1, F2, (1, 1), padding="same", bias=False),
                tnn.BatchNorm2d(F2), tnn.ELU(), tnn.AvgPool2d((1, 8)),
                tnn.Dropout(p), tnn.Flatten(),
            )
            self.classifier = tnn.Linear(F2 * (T // 32), 4, bias=True)

        def forward(self, x):
            x = torch.unsqueeze(x, 1)
            x = self.temporal(x)
            x = self.spatial(x)
            x = self.aggregation(x)
            x = self.block_2(x)
            return self.classifier(x)

    return TorchEEGNet()


def transplant_flax_to_torch(variables, tmodel, F2, t_prime):
    """Copy flax params/batch_stats into the torch model in-place."""
    p = jax.tree_util.tree_map(np.asarray, variables["params"])
    bs = jax.tree_util.tree_map(np.asarray, variables["batch_stats"])

    def conv_w(kernel):  # (kh, kw, in/g, out) -> (out, in/g, kh, kw)
        return torch.tensor(np.transpose(kernel, (3, 2, 0, 1)))

    sd = tmodel.state_dict()
    sd["temporal.0.weight"] = conv_w(p["temporal_conv"]["kernel"])
    sd["temporal.1.weight"] = torch.tensor(p["temporal_bn"]["scale"])
    sd["temporal.1.bias"] = torch.tensor(p["temporal_bn"]["bias"])
    sd["temporal.1.running_mean"] = torch.tensor(bs["temporal_bn"]["mean"])
    sd["temporal.1.running_var"] = torch.tensor(bs["temporal_bn"]["var"])
    sd["spatial.weight"] = conv_w(p["spatial_conv"]["kernel"])
    sd["aggregation.0.weight"] = torch.tensor(p["spatial_bn"]["scale"])
    sd["aggregation.0.bias"] = torch.tensor(p["spatial_bn"]["bias"])
    sd["aggregation.0.running_mean"] = torch.tensor(bs["spatial_bn"]["mean"])
    sd["aggregation.0.running_var"] = torch.tensor(bs["spatial_bn"]["var"])
    sd["block_2.0.weight"] = conv_w(p["separable_depthwise"]["kernel"])
    sd["block_2.1.weight"] = conv_w(p["separable_pointwise"]["kernel"])
    sd["block_2.2.weight"] = torch.tensor(p["block2_bn"]["scale"])
    sd["block_2.2.bias"] = torch.tensor(p["block2_bn"]["bias"])
    sd["block_2.2.running_mean"] = torch.tensor(bs["block2_bn"]["mean"])
    sd["block_2.2.running_var"] = torch.tensor(bs["block2_bn"]["var"])

    # Flax flattens NHWC (1, T', F2) -> index w*F2 + f; torch flattens NCHW
    # (F2, 1, T') -> index f*T' + w.  Permute the classifier input features.
    k = p["classifier"]["kernel"]  # (T'*F2, 4) in flax order
    k_torch = np.zeros((4, F2 * t_prime), dtype=k.dtype)
    for f in range(F2):
        for w in range(t_prime):
            k_torch[:, f * t_prime + w] = k[w * F2 + f, :]
    sd["classifier.weight"] = torch.tensor(k_torch)
    sd["classifier.bias"] = torch.tensor(p["classifier"]["bias"])
    tmodel.load_state_dict(sd)
    tmodel.eval()


@pytest.mark.parametrize("C,T", [(22, 257), (22, 256)])
def test_eval_forward_parity(C, T):
    model = EEGNet(n_channels=C, n_times=T)
    x = np.random.RandomState(0).randn(6, C, T).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x), train=False)

    tmodel = build_torch_eegnet(C=C, T=T)
    transplant_flax_to_torch(variables, tmodel, F2=16, t_prime=T // 32)

    flax_out = np.asarray(model.apply(variables, jnp.asarray(x), train=False))
    with torch.no_grad():
        torch_out = tmodel(torch.tensor(x)).numpy()

    np.testing.assert_allclose(flax_out, torch_out, rtol=1e-4, atol=1e-5)


def test_parity_with_perturbed_bn_stats():
    """Parity must hold with non-trivial running stats, not just init."""
    model = EEGNet()
    x = np.random.RandomState(1).randn(4, 22, 257).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(1), jnp.asarray(x), train=False)

    # Run a few train-mode passes so running stats move off (0, 1).
    vars_mut = variables
    for seed in range(3):
        _, updates = model.apply(
            vars_mut, jnp.asarray(x), train=True,
            rngs={"dropout": jax.random.PRNGKey(seed)}, mutable=["batch_stats"],
        )
        vars_mut = {"params": vars_mut["params"],
                    "batch_stats": updates["batch_stats"]}

    tmodel = build_torch_eegnet()
    transplant_flax_to_torch(vars_mut, tmodel, F2=16, t_prime=8)

    flax_out = np.asarray(model.apply(vars_mut, jnp.asarray(x), train=False))
    with torch.no_grad():
        torch_out = tmodel(torch.tensor(x)).numpy()
    np.testing.assert_allclose(flax_out, torch_out, rtol=1e-4, atol=1e-5)
