"""Tests of the label-verification utility (notebook 06 twin).

The reference validates eval labels manually in
``notebooks/06_eval_data.ipynb`` cells 3-10; ``data/verify.py`` is the
runnable equivalent.  These tests build a synthetic processed tree with a
known cue/label layout and check every verdict the verifier can return.
"""

import shutil
import tempfile
import unittest
from pathlib import Path

import numpy as np
from scipy.io import savemat

from eegnetreplication_tpu.config import Paths
from eegnetreplication_tpu.data.preprocess import ProcessedRecording
from eegnetreplication_tpu.data.verify import verify_labels, verify_session

SFREQ = 128.0


def _write_session(paths: Paths, stem: str, mode: str, cue_typ, classlabel,
                   n_samples: int = 4000):
    """One -preprocessed.npz + its TrueLabels .mat."""
    rng = np.random.RandomState(hash(stem) % 2**31)
    pos = (np.arange(len(cue_typ)) * 450 + 100).astype(np.int64)
    rec = ProcessedRecording(
        data=rng.randn(4, n_samples).astype(np.float32), sfreq=SFREQ,
        labels=[f"C{i}" for i in range(4)], event_pos=pos,
        event_typ=np.asarray(cue_typ, np.int64))
    rec.save(paths.data_processed / mode / f"{stem}-preprocessed.npz")
    tl = paths.data_raw / "TrueLabels"
    tl.mkdir(parents=True, exist_ok=True)
    savemat(tl / f"{stem}.mat", {"classlabel": np.asarray(classlabel)})


class TestVerifyLabels(unittest.TestCase):
    def setUp(self):
        self.tmp = Path(tempfile.mkdtemp(prefix="eegtpu_verify_"))
        self.paths = Paths.from_root(self.tmp)

    def tearDown(self):
        shutil.rmtree(self.tmp, ignore_errors=True)

    def test_train_session_agreement(self):
        # Cues 769..772 -> classes 0..3; classlabel is 1-based.
        cues = [769, 770, 771, 772, 770, 769, 772, 771]
        classlabel = [1, 2, 3, 4, 2, 1, 4, 3]
        _write_session(self.paths, "A01T", "Train", cues, classlabel)
        r = verify_session("A01T", "Train", self.paths)
        self.assertTrue(r.ok, r.errors)
        self.assertEqual(r.n_compared, 8)
        self.assertEqual(r.n_mismatched, 0)
        self.assertEqual(r.classes_seen, (0, 1, 2, 3))

    def test_train_session_mismatch_detected(self):
        cues = [769, 770, 771, 772]
        _write_session(self.paths, "A02T", "Train", cues, [1, 2, 4, 3])
        r = verify_session("A02T", "Train", self.paths)
        self.assertFalse(r.ok)
        self.assertEqual(r.n_mismatched, 2)
        self.assertIn("disagree", r.errors[0])

    def test_count_mismatch_detected(self):
        cues = [783] * 6
        _write_session(self.paths, "A03E", "Eval", cues, [1, 2, 3, 4])
        r = verify_session("A03E", "Eval", self.paths)
        self.assertFalse(r.ok)
        self.assertIn("cue events", r.errors[0])

    def test_eval_session_ok(self):
        cues = [783] * 8
        _write_session(self.paths, "A04E", "Eval", cues, [1, 2, 3, 4] * 2)
        r = verify_session("A04E", "Eval", self.paths)
        self.assertTrue(r.ok, r.errors)
        self.assertEqual(r.n_cue_events, 8)
        self.assertEqual(r.classes_seen, (0, 1, 2, 3))

    def test_missing_class_flagged(self):
        cues = [769, 770, 769, 770]
        _write_session(self.paths, "A05T", "Train", cues, [1, 2, 1, 2])
        r = verify_session("A05T", "Train", self.paths)
        self.assertFalse(r.ok)
        self.assertTrue(any("classes" in e for e in r.errors))

    def test_missing_files_reported_not_raised(self):
        r = verify_session("A09T", "Train", self.paths)
        self.assertFalse(r.ok)
        self.assertIn("no preprocessed recording", r.errors[0])
        # recording present, .mat absent
        rng = np.random.RandomState(0)
        rec = ProcessedRecording(
            data=rng.randn(4, 4000).astype(np.float32), sfreq=SFREQ,
            labels=["C0"], event_pos=np.array([100], np.int64),
            event_typ=np.array([769], np.int64))
        rec.save(self.paths.data_processed / "Train" / "A09T-preprocessed.npz")
        r = verify_session("A09T", "Train", self.paths)
        self.assertFalse(r.ok)
        self.assertIn("True labels not found", r.errors[0])

    def test_verify_labels_sweeps_both_modes(self):
        cues_t = [769, 770, 771, 772]
        cues_e = [783] * 4
        for s in (1, 2):
            _write_session(self.paths, f"A0{s}T", "Train", cues_t,
                           [1, 2, 3, 4])
            _write_session(self.paths, f"A0{s}E", "Eval", cues_e,
                           [4, 3, 2, 1])
        results = verify_labels(subjects=(1, 2), mode="both",
                                paths=self.paths)
        self.assertEqual(len(results), 4)
        self.assertTrue(all(r.ok for r in results),
                        [r.errors for r in results if not r.ok])


if __name__ == "__main__":
    unittest.main()
