"""Parity of the banded-matmul conv path against the lax conv path.

The model exposes two op schedules for the same math (``EEGNet.conv_impl``):
``lax`` convs (minimal FLOPs) and ``banded`` matmuls (the MXU schedule the
fold-vmapped training protocols use on TPU — ``ops/banded.py``).  Science
must not depend on the schedule: these tests pin init equality (bit-exact),
forward/backward/BN-update parity (f32-rounding tolerance), and short
training-trajectory agreement between the two.

Reference ops under test: the torch convs of
``src/eegnet_repl/model.py:22-76``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from eegnetreplication_tpu.models.eegnet import EEGNet
from eegnetreplication_tpu.ops import banded
from eegnetreplication_tpu.training.steps import (
    TrainState,
    make_optimizer,
    train_step,
)

C, T = 10, 65  # small but structure-complete (T//32 >= 1)


def models():
    kw = dict(n_channels=C, n_times=T, F1=4, D=2, dropout_rate=0.5)
    return (EEGNet(conv_impl="lax", **kw), EEGNet(conv_impl="banded", **kw))


@pytest.fixture(scope="module")
def batch():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(12, C, T).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 4, size=12))
    return x, y


class TestOpParity:
    """Each banded op against its lax twin, standalone."""

    def test_temporal_conv(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(3, C, T, 1).astype(np.float32))
        k = jnp.asarray(rng.randn(1, 32, 1, 4).astype(np.float32))
        ref = jax.lax.conv_general_dilated(
            x, k, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"),
            precision=jax.lax.Precision.HIGHEST)
        got = banded.temporal_conv_banded(x, k, precision="highest")
        np.testing.assert_allclose(got, ref, atol=2e-5, rtol=1e-5)

    def test_spatial_grouped_conv(self):
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(3, C, T, 4).astype(np.float32))
        k = jnp.asarray(rng.randn(C, 1, 1, 8).astype(np.float32))
        ref = jax.lax.conv_general_dilated(
            x, k, (1, 1), "VALID", dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=4, precision=jax.lax.Precision.HIGHEST)
        got = banded.spatial_conv_banded(x, k, precision="highest")
        np.testing.assert_allclose(got, ref, atol=2e-5, rtol=1e-5)

    def test_depthwise_conv(self):
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(3, 1, 16, 8).astype(np.float32))
        k = jnp.asarray(rng.randn(1, 16, 1, 8).astype(np.float32))
        ref = jax.lax.conv_general_dilated(
            x, k, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=8, precision=jax.lax.Precision.HIGHEST)
        got = banded.depthwise_conv_banded(x, k, precision="highest")
        np.testing.assert_allclose(got, ref, atol=2e-5, rtol=1e-5)

    def test_pointwise_conv(self):
        rng = np.random.RandomState(4)
        x = jnp.asarray(rng.randn(3, 1, 16, 8).astype(np.float32))
        k = jnp.asarray(rng.randn(1, 1, 8, 8).astype(np.float32))
        ref = jax.lax.conv_general_dilated(
            x, k, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"),
            precision=jax.lax.Precision.HIGHEST)
        got = banded.pointwise_conv_banded(x, k, precision="highest")
        np.testing.assert_allclose(got, ref, atol=2e-5, rtol=1e-5)

    def test_avg_pool(self):
        import flax.linen as nn

        rng = np.random.RandomState(5)
        x = jnp.asarray(rng.randn(3, 1, 65, 8).astype(np.float32))
        ref = nn.avg_pool(x, (1, 4), strides=(1, 4))
        got = banded.avg_pool_width(x, 4)
        np.testing.assert_allclose(got, ref, atol=1e-6)


class TestModelParity:
    def test_init_bit_identical(self, batch):
        lax_m, band_m = models()
        x, _ = batch
        key = jax.random.PRNGKey(7)
        v1 = lax_m.init(key, x[:2])
        v2 = band_m.init(key, x[:2])
        assert jax.tree_util.tree_structure(v1) == \
            jax.tree_util.tree_structure(v2)
        for a, b in zip(jax.tree_util.tree_leaves(v1),
                        jax.tree_util.tree_leaves(v2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_eval_forward(self, batch):
        lax_m, band_m = models()
        x, _ = batch
        v = lax_m.init(jax.random.PRNGKey(7), x[:2])
        ref = lax_m.apply(v, x, train=False)
        got = band_m.apply(v, x, train=False)
        np.testing.assert_allclose(got, ref, atol=3e-5, rtol=1e-4)

    def test_train_forward_and_bn_updates(self, batch):
        lax_m, band_m = models()
        x, _ = batch
        v = lax_m.init(jax.random.PRNGKey(7), x[:2])
        drng = jax.random.PRNGKey(11)
        ref, ref_upd = lax_m.apply(v, x, train=True,
                                   mutable=["batch_stats"],
                                   rngs={"dropout": drng})
        got, got_upd = band_m.apply(v, x, train=True,
                                    mutable=["batch_stats"],
                                    rngs={"dropout": drng})
        np.testing.assert_allclose(got, ref, atol=5e-5, rtol=1e-4)
        for a, b in zip(jax.tree_util.tree_leaves(ref_upd),
                        jax.tree_util.tree_leaves(got_upd)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=5e-5, rtol=1e-4)

    def test_gradients(self, batch):
        lax_m, band_m = models()
        x, y = batch
        v = lax_m.init(jax.random.PRNGKey(7), x[:2])
        drng = jax.random.PRNGKey(13)

        def loss(model, params):
            import optax

            logits, _ = model.apply(
                {"params": params, "batch_stats": v["batch_stats"]}, x,
                train=True, mutable=["batch_stats"],
                rngs={"dropout": drng})
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()

        g_ref = jax.grad(lambda p: loss(lax_m, p))(v["params"])
        g_got = jax.grad(lambda p: loss(band_m, p))(v["params"])
        for (pa, a), (_, b) in zip(
                jax.tree_util.tree_leaves_with_path(g_ref),
                jax.tree_util.tree_leaves_with_path(g_got)):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), atol=1e-4, rtol=2e-3,
                err_msg=jax.tree_util.keystr(pa))

    def test_short_training_trajectory(self, batch):
        """30 train steps under each schedule: endpoint params agree to
        f32-accumulation tolerance (the schedules reorder summations, so
        bit-equality is not the contract — trajectory closeness is)."""
        lax_m, band_m = models()
        x, y = batch
        w = jnp.ones(x.shape[0])
        tx = make_optimizer()

        def run(model):
            v = model.init(jax.random.PRNGKey(7), x[:2])
            state = TrainState.create(v, tx)
            losses = []
            for i in range(30):
                state, loss = jax.jit(
                    train_step, static_argnames=("model", "tx",
                                                 "maxnorm_mode"))(
                    model, tx, state, x, y, w, jax.random.PRNGKey(100 + i))
                losses.append(float(loss))
            return state, losses

        s_ref, l_ref = run(lax_m)
        s_got, l_got = run(band_m)
        np.testing.assert_allclose(l_got, l_ref, atol=5e-4, rtol=5e-3)
        for (pa, a), (_, b) in zip(
                jax.tree_util.tree_leaves_with_path(s_ref.params),
                jax.tree_util.tree_leaves_with_path(s_got.params)):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), atol=5e-3, rtol=5e-2,
                err_msg=jax.tree_util.keystr(pa))

    def test_fold_vmapped_step_runs(self, batch):
        """The protocols' shape: vmap the train step over a fold axis of
        per-fold params — the banded einsums must batch into dot_generals
        (correctness; the perf claim is measured on chip)."""
        _, band_m = models()
        x, y = batch
        n_folds = 3
        tx = make_optimizer()
        keys = jax.random.split(jax.random.PRNGKey(0), n_folds)
        states = jax.vmap(
            lambda k: TrainState.create(band_m.init(k, x[:2]), tx))(keys)
        w = jnp.ones(x.shape[0])

        def step(state, key):
            return train_step(band_m, tx, state, x, y, w, key)

        new_states, losses = jax.jit(jax.vmap(step))(states, keys)
        assert losses.shape == (n_folds,)
        assert np.all(np.isfinite(np.asarray(losses)))
        # Distinct per-fold inits must stay distinct after the step.
        k0 = np.asarray(new_states.params["temporal_conv"]["kernel"])
        assert not np.allclose(k0[0], k0[1])


class TestAutoResolution:
    """conv_impl='auto' resolves at CONSTRUCTION (ADVICE r4): the resolved
    schedule enters the module's hash/equality so jit caches cannot
    conflate programs compiled under different env values, and 'auto'
    guards against banded's O(T^2) expansion at long T."""

    def test_auto_resolves_to_banded_at_protocol_length(self):
        m = EEGNet(n_channels=22, n_times=257)
        assert m.conv_impl == "banded"

    def test_auto_stays_banded_at_long_t(self):
        """At native 250 Hz length (T=1125) the banded ops tile the time
        axis (bounded memory, ~tile/K inflation), and the on-chip A/B
        measured tiled-banded 4.94x lax — 'auto' stays banded."""
        m = EEGNet(n_channels=22, n_times=1125)
        assert m.conv_impl == "banded"

    def test_env_override_applies_at_construction(self, monkeypatch):
        monkeypatch.setenv("EEGTPU_CONV_IMPL", "lax")
        assert EEGNet(n_channels=22, n_times=257).conv_impl == "lax"
        # Env changes cannot retarget an ALREADY-constructed module.
        monkeypatch.setenv("EEGTPU_CONV_IMPL", "banded")
        m = EEGNet(n_channels=22, n_times=257)
        monkeypatch.setenv("EEGTPU_CONV_IMPL", "lax")
        assert m.conv_impl == "banded"

    def test_modules_under_different_env_values_are_unequal(self,
                                                            monkeypatch):
        """The jit-cache hazard itself: two 'auto' modules constructed
        under different env values must not compare equal."""
        monkeypatch.setenv("EEGTPU_CONV_IMPL", "banded")
        a = EEGNet(n_channels=C, n_times=T)
        monkeypatch.setenv("EEGTPU_CONV_IMPL", "lax")
        b = EEGNet(n_channels=C, n_times=T)
        assert a != b

    def test_invalid_values_rejected(self, monkeypatch):
        with pytest.raises(ValueError, match="conv_impl"):
            EEGNet(conv_impl="cudnn")
        monkeypatch.setenv("EEGTPU_CONV_IMPL", "winograd")
        with pytest.raises(ValueError, match="conv_impl"):
            EEGNet(n_channels=C, n_times=T)


class TestTiledLongT:
    """Past BANDED_TILE_T the banded ops tile the time axis: one
    (tile+K-1, tile) band shared across tiles — O(K*tile^2) memory and
    ~tile/K MAC inflation independent of T.  Numerics must match both the
    untiled banded form and lax convs exactly."""

    def test_conv1d_tiled_matches_untiled(self):
        from eegnetreplication_tpu.ops.banded import (
            conv1d_same_banded,
            conv1d_same_banded_tiled,
            same_pad_1d,
        )

        rng = np.random.RandomState(0)
        for t_out, tile in ((300, 128), (257, 256), (513, 256), (640, 256)):
            taps = jnp.asarray(rng.randn(32, 4).astype(np.float32))
            x = jnp.asarray(rng.randn(3, 5, t_out).astype(np.float32))
            xp = same_pad_1d(x, 32)
            # Untiled reference built directly (bypass the dispatch).
            from eegnetreplication_tpu.ops.banded import _expansion_host
            e = jnp.asarray(_expansion_host(32, t_out))
            band = jnp.einsum("kpt,kf->ptf", e, taps)
            ref = jnp.einsum("...p,ptf->...tf", xp, band)
            got = conv1d_same_banded_tiled(xp, taps, t_out, tile=tile)
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       atol=1e-5,
                                       err_msg=f"t_out={t_out} tile={tile}")

    @pytest.mark.slow
    def test_long_t_model_matches_lax_forward_and_grads(self):
        """EEGNet at a long time axis (banded => tiled path) must match
        the lax schedule through the full model and one training step."""
        import optax

        long_t = 1125  # native 250 Hz BCI-IV-2a epoch length
        kw = dict(n_channels=6, n_times=long_t, F1=4, D=2,
                  dropout_rate=0.0)
        m_lax = EEGNet(conv_impl="lax", **kw)
        m_band = EEGNet(conv_impl="banded", **kw)
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(4, 6, long_t).astype(np.float32))
        y = jnp.asarray(rng.randint(0, 4, size=4))
        w = jnp.ones(4, jnp.float32)
        v = m_lax.init(jax.random.PRNGKey(0), x)
        out_lax = m_lax.apply(v, x, train=False)
        out_band = m_band.apply(v, x, train=False)
        np.testing.assert_allclose(np.asarray(out_band),
                                   np.asarray(out_lax), atol=2e-4)
        tx = make_optimizer(1e-3)
        s0 = TrainState.create(v, tx)
        s_lax, l_lax = train_step(m_lax, tx, s0, x, y, w,
                                  jax.random.PRNGKey(2))
        s_band, l_band = train_step(m_band, tx, s0, x, y, w,
                                    jax.random.PRNGKey(2))
        assert float(l_lax) == pytest.approx(float(l_band), abs=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(s_lax.params),
                        jax.tree_util.tree_leaves(s_band.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4)
