"""Async snapshot writer + the sharded-training selftest tier-1 leg.

Unit coverage for ``training/async_ckpt.SnapshotWriter`` (ordering, error
surfacing, drain hooks, the journal's ``checkpoint_write`` evidence) and
for the bounded/lock-guarded ``orbax_io._ASYNC_PENDING`` set, plus the
CI-sized ``scripts/cs_at_scale.py --selftest`` A/B that writes
``BENCH_CS_SHARD.json`` (sharded+async throughput >= unsharded+sync with
zero blocking-write stalls).
"""

import importlib.util
import json
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from eegnetreplication_tpu import obs
from eegnetreplication_tpu.obs import schema
from eegnetreplication_tpu.resil import inject, preempt
from eegnetreplication_tpu.training import checkpoint as ckpt_lib
from eegnetreplication_tpu.training.async_ckpt import (
    SnapshotWriteError,
    SnapshotWriter,
)

REPO = Path(__file__).resolve().parents[1]

SIG = {"protocol": "test", "model": "toy", "subjects": [1]}


def _carry(step: int):
    return {"w": np.full((4, 3), float(step), np.float32),
            "b": np.arange(4, dtype=np.float32) + step}


def _metrics(step: int):
    return {"train_losses": np.full((2, step), 0.5, np.float32)}


def _events(jr):
    return schema.read_events(jr.events_path, complete=False)


def _writes(jr):
    return [e for e in _events(jr) if e["event"] == "checkpoint_write"]


class TestSnapshotWriter:
    def test_async_writes_land_in_order_and_rotate(self, tmp_path):
        path = tmp_path / "m" / "run.npz"
        with obs.run(tmp_path / "obs") as jr:
            w = SnapshotWriter(path, SIG, journal=jr)
            for step in (1, 2, 3):
                w.submit(_carry(step), _metrics(step), epochs_done=2 * step)
            w.close()
            writes = _writes(jr)
        carry, _, epochs_done = ckpt_lib.load_run_snapshot(
            path, _carry(0), SIG)
        assert epochs_done == 6  # newest generation wins
        np.testing.assert_array_equal(carry["w"], _carry(3)["w"])
        # keep-N rotation kept a previous generation beside the newest.
        assert list(path.parent.glob("run.npz.gen*"))
        assert [e["generation"] for e in writes] == [1, 2, 3]
        assert all(e["async"] for e in writes)
        # The final write is journaled at close() as shutdown drain; the
        # in-loop ones are not.
        assert [bool(e.get("drain")) for e in writes] == [False, False, True]

    def test_sync_mode_blocks_inline(self, tmp_path):
        path = tmp_path / "run.npz"
        with obs.run(tmp_path / "obs") as jr:
            w = SnapshotWriter(path, SIG, async_=False, journal=jr)
            w.submit(_carry(1), _metrics(1), epochs_done=2)
            writes = _writes(jr)  # journaled AT submit, not at close
            assert len(writes) == 1
            w.close()
        (e,) = writes
        assert not e["async"] and not e.get("drain")
        # A synchronous write is 100% blocking: the step loop waited out
        # the full serialize+write+rename.
        assert e["blocked_ms"] == e["dur_ms"]
        assert e["overlapped_ms"] == 0.0

    def test_background_failure_surfaces_on_next_submit(self, tmp_path):
        blocker = tmp_path / "m"
        blocker.write_text("not a directory")  # parent mkdir will fail
        with obs.run(tmp_path / "obs") as jr:
            w = SnapshotWriter(blocker / "run.npz", SIG, journal=jr)
            w.submit(_carry(1), _metrics(1), epochs_done=2)
            with pytest.raises(SnapshotWriteError, match="failed"):
                w.submit(_carry(2), _metrics(2), epochs_done=4)
            w.close(raise_errors=False)  # exception path: logged, not raised

    def test_close_raises_on_failed_final_write(self, tmp_path):
        blocker = tmp_path / "m"
        blocker.write_text("not a directory")
        with obs.run(tmp_path / "obs") as jr:
            w = SnapshotWriter(blocker / "run.npz", SIG, journal=jr)
            w.submit(_carry(1), _metrics(1), epochs_done=2)
            with pytest.raises(SnapshotWriteError):
                w.close()

    def test_submit_after_close_raises(self, tmp_path):
        w = SnapshotWriter(tmp_path / "run.npz", SIG, async_=False)
        w.close()
        with pytest.raises(SnapshotWriteError, match="closed"):
            w.submit(_carry(1), _metrics(1), epochs_done=2)

    def test_preempt_drain_commits_pending_write(self, tmp_path):
        path = tmp_path / "run.npz"
        with obs.run(tmp_path / "obs") as jr:
            w = SnapshotWriter(path, SIG, journal=jr)
            w.submit(_carry(1), _metrics(1), epochs_done=2)
            # A graceful stop unwinding past the protocol runs the drain
            # hooks — the in-flight snapshot must be durable afterwards.
            preempt.run_drain_hooks()
        _, _, epochs_done = ckpt_lib.load_run_snapshot(path, _carry(0), SIG)
        assert epochs_done == 2
        with pytest.raises(SnapshotWriteError, match="closed"):
            w.submit(_carry(2), _metrics(2), epochs_done=4)

    def test_slow_write_degrades_to_blocking_not_queueing(self, tmp_path):
        """At most one write in flight: a fast submitter waits for the
        previous write (ordered snapshots), it never queues unboundedly."""
        path = tmp_path / "run.npz"
        orig = ckpt_lib.save_run_snapshot

        def slow_save(*a, **kw):
            time.sleep(0.05)
            return orig(*a, **kw)

        with obs.run(tmp_path / "obs") as jr:
            w = SnapshotWriter(path, SIG, journal=jr)
            try:
                ckpt_lib.save_run_snapshot = slow_save
                w.submit(_carry(1), _metrics(1), epochs_done=2)
                w.submit(_carry(2), _metrics(2), epochs_done=4)  # waits
            finally:
                ckpt_lib.save_run_snapshot = orig
            w.close()
            writes = _writes(jr)
        assert [e["epochs_done"] for e in writes] == [2, 4]
        # The second submit's join really waited on write 1.
        assert writes[0]["blocked_ms"] > 0


class TestAsyncInjectSite:
    def test_write_async_site_fires_only_inside_writer(self, tmp_path):
        """The ``checkpoint.write_async`` chaos phase arms the BACKGROUND
        writer's write without touching the synchronous path."""
        sync_path = tmp_path / "sync.npz"
        async_path = tmp_path / "async.npz"
        with inject.scoped(inject.FaultSpec(site="checkpoint.write_async",
                                            times=0)):
            ckpt_lib.save_run_snapshot(sync_path, _carry(1), _metrics(1),
                                       epochs_done=2, signature=SIG)
            w = SnapshotWriter(async_path, SIG)
            w.submit(_carry(1), _metrics(1), epochs_done=2)
            w.close(raise_errors=False)
        # Sync write untouched; the async generation was torn mid-write
        # and fails content integrity on resolve (quarantined).
        _, _, epochs_done = ckpt_lib.load_run_snapshot(
            sync_path, _carry(0), SIG)
        assert epochs_done == 2
        with pytest.raises(FileNotFoundError):
            ckpt_lib.load_run_snapshot(async_path, _carry(0), SIG)
        assert list(tmp_path.glob("async.npz*.corrupt"))


class TestOrbaxPendingBound:
    def test_pending_set_is_bounded(self, tmp_path, monkeypatch):
        pytest.importorskip("orbax.checkpoint")
        import jax
        import jax.numpy as jnp

        from eegnetreplication_tpu.models import EEGNet
        from eegnetreplication_tpu.training import orbax_io

        model = EEGNet(n_channels=8, n_times=64)
        variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 64)),
                               train=False)
        monkeypatch.setattr(orbax_io, "MAX_ASYNC_PENDING", 2)
        try:
            for i in range(5):
                orbax_io.save_orbax_checkpoint(
                    tmp_path / f"ck{i}", variables["params"],
                    variables["batch_stats"], {"i": i}, background=True)
                assert orbax_io._pending_count() <= 2
        finally:
            orbax_io.wait_for_async_saves()
        assert orbax_io._pending_count() == 0
        # Every save committed (oldest entries were drained, not dropped).
        for i in range(5):
            _, _, meta = orbax_io.load_orbax_checkpoint(tmp_path / f"ck{i}")
            assert meta == {"i": i}


class TestSelftestLeg:
    def test_cs_shard_selftest(self, tmp_path):
        """The BENCH_CS_SHARD acceptance: sharded+async >= unsharded+sync
        with zero blocking-write stalls and accuracy parity, CI-sized."""
        spec = importlib.util.spec_from_file_location(
            "cs_at_scale", REPO / "scripts" / "cs_at_scale.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        rc = mod.selftest(tmp_path, epochs=10)
        record = json.loads((tmp_path / "BENCH_CS_SHARD.json").read_text())
        assert rc == 0 and record["ok"], record.get("error")
        shard = record["arms"]["sharded_async"]
        sync = record["arms"]["unsharded_sync"]
        assert shard["stalled_writes"] == 0
        assert shard["checkpoint_writes"] > 0
        assert record["sharded_over_unsharded"] >= 1.0
        # The sync arm's writes all blocked the loop — the A/B is real.
        assert sync["stalled_writes"] == sync["checkpoint_writes"]
        assert shard["avg_test_acc"] == pytest.approx(
            sync["avg_test_acc"], abs=0.5)
