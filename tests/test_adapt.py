"""Closed-loop online adaptation: gate policy, replay buffer bounds, the
``POST /session/<id>/label`` contract, label durability across
snapshot/resume and export/import, and the ``adapt_bench.py --selftest``
acceptance leg (drift -> labeled replay -> fine-tune -> shadow ->
promotion -> recovery, plus rollback under load).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from eegnetreplication_tpu.adapt.buffer import ReplayBuffer  # noqa: E402
from eegnetreplication_tpu.adapt.gate import PromotionGate  # noqa: E402
from eegnetreplication_tpu.models import EEGNet  # noqa: E402
from eegnetreplication_tpu.obs import journal as obs_journal  # noqa: E402
from eegnetreplication_tpu.obs import schema  # noqa: E402
from eegnetreplication_tpu.serve.service import ServeApp  # noqa: E402
from eegnetreplication_tpu.serve.sessions import (  # noqa: E402
    SessionStore,
    StreamSession,
    WindowDecision,
)
from eegnetreplication_tpu.serve.sessions.session import (  # noqa: E402
    STATUS_EXPIRED,
    STATUS_OK,
    LabelConflict,
)
from eegnetreplication_tpu.training.checkpoint import (  # noqa: E402
    save_checkpoint,
)

REPO = Path(__file__).resolve().parent.parent

C, T = 4, 64
HOP = 16
BLOCK = 256


# ---------------------------------------------------------------------------
# PromotionGate: pure policy over the evaluator's cumulative stats.


def _stats(n_trials=20, labeled_n=10, agreement=0.8, accuracy=0.9):
    return {"n_trials": n_trials, "labeled_n": labeled_n,
            "agreement": agreement, "accuracy": accuracy}


class TestPromotionGate:
    def test_waits_for_shadow_samples_then_labeled_evidence(self):
        gate = PromotionGate(min_samples=12, min_labeled=8)
        d = gate.decide(_stats(n_trials=11))
        assert d.action == "wait" and "shadow samples" in d.reason
        d = gate.decide(_stats(n_trials=12, labeled_n=7))
        assert d.action == "wait" and "labeled evals" in d.reason

    def test_promotes_only_above_accuracy_floor(self):
        gate = PromotionGate(min_samples=4, min_labeled=4,
                             accuracy_floor=0.55)
        good = gate.decide(_stats(n_trials=8, labeled_n=8, accuracy=0.75))
        assert good.action == "promote"
        assert good.labeled_n == 8 and good.accuracy == 0.75
        bad = gate.decide(_stats(n_trials=8, labeled_n=8, accuracy=0.5))
        assert bad.action == "refuse" and "accuracy" in bad.reason

    def test_agreement_floor_disabled_by_default(self):
        """After a real drift the live model is the wrong reference, so
        agreement must not gate by default — only when opted into."""
        gate = PromotionGate(min_samples=1, min_labeled=1)
        assert gate.decide(_stats(agreement=0.0)).action == "promote"
        canary = PromotionGate(min_samples=1, min_labeled=1,
                               agreement_floor=0.6)
        d = canary.decide(_stats(agreement=0.3))
        assert d.action == "refuse" and "agreement" in d.reason

    def test_constructor_validation(self):
        for kw in ({"min_samples": 0}, {"min_labeled": 0},
                   {"accuracy_floor": 1.5}, {"agreement_floor": -0.1}):
            with pytest.raises(ValueError):
                PromotionGate(**kw)

    def test_config_roundtrip(self):
        gate = PromotionGate(min_samples=3, min_labeled=2,
                             accuracy_floor=0.6, agreement_floor=0.1)
        assert gate.config() == {"min_samples": 3, "min_labeled": 2,
                                 "accuracy_floor": 0.6,
                                 "agreement_floor": 0.1}


# ---------------------------------------------------------------------------
# ReplayBuffer: bounded capture ring + labeled set.


def _win(seed: int) -> np.ndarray:
    return np.random.RandomState(seed).randn(C, T).astype(np.float32)


class TestReplayBuffer:
    def test_observe_then_label_pairs_the_exact_window(self):
        buf = ReplayBuffer()
        w = _win(0)
        buf.observe("m", "s", 0, w)
        assert buf.label("m", "s", 0, 2) is True
        assert buf.n_labeled("m") == 1
        x, y = buf.dataset("m")
        np.testing.assert_array_equal(x[0], w)
        assert y.tolist() == [2]
        np.testing.assert_array_equal(buf.window_for("m", "s", 0), w)

    def test_label_without_capture_is_counted_not_fatal(self):
        buf = ReplayBuffer()
        assert buf.label("m", "s", 99, 1) is False
        assert buf.stats("m")["unpaired_labels"] == 1
        assert buf.n_labeled("m") == 0

    def test_capture_ring_evicts_oldest(self):
        buf = ReplayBuffer(window_capacity=4)
        for i in range(6):
            buf.observe("m", "s", i, _win(i))
        # Windows 0 and 1 aged out of the ring: labeling them finds
        # nothing to train on, the newest four still pair.
        assert buf.label("m", "s", 0, 1) is False
        assert buf.label("m", "s", 5, 1) is True

    def test_labeled_set_is_bounded_fifo(self):
        buf = ReplayBuffer(window_capacity=16, labeled_capacity=3)
        for i in range(5):
            buf.observe("m", "s", i, _win(i))
            buf.label("m", "s", i, i % 4)
        assert buf.n_labeled("m") == 3
        x, y = buf.dataset("m")
        assert y.tolist() == [2 % 4, 3 % 4, 4 % 4]

    def test_relabel_of_paired_window_overwrites_y(self):
        buf = ReplayBuffer()
        buf.observe("m", "s", 0, _win(0))
        buf.label("m", "s", 0, 1)
        # The session layer enforces idempotence/conflicts; the buffer
        # treats a re-label as an overwrite of y only.
        assert buf.label("m", "s", 0, 3) is True
        _, y = buf.dataset("m")
        assert y.tolist() == [3]
        assert buf.n_labeled("m") == 1

    def test_tenants_are_isolated_and_clearable(self):
        buf = ReplayBuffer()
        buf.observe("a", "s", 0, _win(0))
        buf.label("a", "s", 0, 1)
        assert buf.n_labeled("b") == 0
        buf.clear("a")
        assert buf.n_labeled("a") == 0
        assert buf.dataset("a")[0].shape == (0,)


# ---------------------------------------------------------------------------
# Session-layer label semantics (unit level, incl. the expired case the
# HTTP path can't trigger deterministically).


def _decided_session(n_windows: int = 4,
                     store: SessionStore | None = None) -> StreamSession:
    kwargs = dict(n_channels=C, window=T, hop=HOP,
                  ems_init_block_size=BLOCK)
    if store is None:
        session = StreamSession("s", **kwargs)
    else:
        session, resumed = store.open("s", **kwargs)
        assert not resumed
    rng = np.random.RandomState(3)
    ready = session.ingest(rng.randn(C, BLOCK + T + HOP * n_windows)
                           .astype(np.float32))
    for idx, start, _ in ready[:n_windows]:
        session.record(WindowDecision(index=idx, start=start, pred=idx % 4,
                                      status=STATUS_OK, latency_ms=1.0))
    assert session.windows_decided >= n_windows
    return session


class TestSessionLabelSemantics:
    def test_expired_window_is_a_conflict_not_a_crash(self):
        session = StreamSession("s", n_channels=C, window=T, hop=HOP)
        session.record(WindowDecision(index=0, start=0, pred=-1,
                                      status=STATUS_EXPIRED, latency_ms=9.0))
        with pytest.raises(LabelConflict, match="expired"):
            session.label(0, 2)

    def test_unknown_window_raises_keyerror_with_frontier(self):
        session = _decided_session()
        with pytest.raises(KeyError, match="frontier"):
            session.label(session.windows_decided, 0)

    def test_duplicate_and_conflict(self):
        session = _decided_session()
        assert session.label(1, 3) is True
        assert session.label(1, 3) is False      # idempotent retry
        with pytest.raises(LabelConflict, match="refusing"):
            session.label(1, 2)

    def test_labels_survive_state_roundtrip(self):
        session = _decided_session()
        session.label(0, 2)
        session.label(3, 1)
        restored = StreamSession.from_state("s", session.state_arrays())
        assert restored.labels == {0: 2, 3: 1}
        # And the restored session still enforces the conflict contract.
        assert restored.label(0, 2) is False
        with pytest.raises(LabelConflict):
            restored.label(3, 0)

    def test_pre_adaptation_snapshot_restores_labelless(self):
        session = _decided_session()
        session.label(0, 2)
        flat = session.state_arrays()
        del flat["lab_window"], flat["lab_label"]
        assert StreamSession.from_state("s", flat).labels == {}

    def test_labels_survive_store_snapshot_restore(self, tmp_path):
        store = SessionStore(tmp_path / "sessions.npz")
        session = _decided_session(store=store)
        session.label(2, 3)
        store.snapshot()
        store.detach()
        restored = SessionStore(tmp_path / "sessions.npz")
        assert restored.restore() == ["s"]
        assert restored.get("s").labels == {2: 3}
        restored.detach()

    def test_labels_survive_export_import(self, tmp_path):
        source = SessionStore(tmp_path / "src.npz")
        session = _decided_session(store=source)
        session.label(1, 0)
        wire = source.export_session("s")
        target = SessionStore(tmp_path / "dst.npz")
        imported = target.import_session(wire)
        assert imported.labels == {1: 0}
        source.detach()
        target.detach()


# ---------------------------------------------------------------------------
# HTTP label endpoint contract.


def _checkpoint(tmp_path: Path) -> Path:
    model = EEGNet(n_channels=C, n_times=T)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, C, T)),
                           train=False)
    return save_checkpoint(
        tmp_path / "m.npz", variables["params"], variables["batch_stats"],
        metadata={"model": "eegnet", "n_channels": C, "n_times": T,
                  "F1": model.F1, "D": model.D})


def _post(url, data, ctype="application/json"):
    req = urllib.request.Request(url, data=data,
                                 headers={"Content-Type": ctype})
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read().decode())


class TestLabelEndpointHTTP:
    @pytest.fixture
    def app(self, tmp_path):
        with obs_journal.run(tmp_path / "obs", config={}) as jr:
            app = ServeApp(_checkpoint(tmp_path), buckets=(1, 8),
                           sessions_dir=tmp_path / "sess",
                           journal=jr).start()
            try:
                yield app, jr
            finally:
                app.stop()

    def _opened(self, app, sid="L1", n_windows=4):
        _post(app.url + "/session/open", json.dumps(
            {"session": sid, "hop": HOP,
             "ems_init_block_size": BLOCK}).encode())
        rec = np.random.RandomState(5).randn(
            C, BLOCK + T + HOP * n_windows).astype(np.float32)
        reply = _post(app.url + f"/session/{sid}/samples",
                      rec.astype("<f4").tobytes(),
                      "application/octet-stream")
        assert len(reply["decisions"]) >= n_windows
        return sid

    def _label(self, app, sid, window, label):
        return _post(app.url + f"/session/{sid}/label",
                     json.dumps({"window": window, "label": label}).encode())

    def test_label_idempotence_conflict_and_journal(self, app):
        app, jr = app
        sid = self._opened(app)
        first = self._label(app, sid, 0, 2)
        assert first["fresh"] is True and first["labels"] == 1
        again = self._label(app, sid, 0, 2)
        assert again["fresh"] is False and again["labels"] == 1
        with pytest.raises(urllib.error.HTTPError) as err:
            self._label(app, sid, 0, 3)
        assert err.value.code == 409
        events = schema.read_events(jr.events_path, complete=False)
        labels = [e for e in events if e["event"] == "session_label"]
        # The idempotent retry and the conflict journal nothing: exactly
        # one session_label event for the one fresh label.
        assert len(labels) == 1
        assert labels[0]["window"] == 0 and labels[0]["label"] == 2
        assert labels[0]["live_pred"] is not None

    def test_unknown_window_and_session_are_404_not_500(self, app):
        app, _ = app
        sid = self._opened(app)
        with pytest.raises(urllib.error.HTTPError) as err:
            self._label(app, sid, 10_000, 1)
        assert err.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as err:
            self._label(app, "ghost", 0, 1)
        assert err.value.code == 404

    def test_malformed_bodies_are_400(self, app):
        app, _ = app
        sid = self._opened(app)
        for body in (b"not json", b"[]", b'{"window": 0}',
                     json.dumps({"window": 0, "label": 99}).encode(),
                     json.dumps({"window": -1, "label": 0}).encode()):
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(app.url + f"/session/{sid}/label", body)
            assert err.value.code == 400, body

    def test_labels_survive_http_export_import(self, app, tmp_path):
        app, _ = app
        sid = self._opened(app, sid="M1")
        self._label(app, sid, 1, 3)
        with urllib.request.urlopen(app.url + f"/session/{sid}/export",
                                    timeout=30) as resp:
            wire = resp.read()
        target = ServeApp(_checkpoint(tmp_path / "t2"), buckets=(1, 8),
                          sessions_dir=tmp_path / "t2_sess").start()
        try:
            _post(app.url + f"/session/{sid}/discard", b"{}")
            _post(target.url + "/session/import", wire,
                  "application/octet-stream")
            # The migrated stream enforces the same label contract:
            # idempotent duplicate, 409 conflict.
            dup = _post(target.url + f"/session/{sid}/label",
                        json.dumps({"window": 1, "label": 3}).encode())
            assert dup["fresh"] is False and dup["labels"] == 1
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(target.url + f"/session/{sid}/label",
                      json.dumps({"window": 1, "label": 0}).encode())
            assert err.value.code == 409
        finally:
            target.stop()

    def test_labeling_works_with_adapt_off(self, app):
        """Labels are durable session state; the adaptation loop is a
        side effect, not a dependency (the fixture app has no --adapt)."""
        app, _ = app
        sid = self._opened(app)
        reply = self._label(app, sid, 2, 1)
        assert reply["fresh"] is True and reply["paired"] is False


# ---------------------------------------------------------------------------
# The acceptance leg: drift -> labels -> fine-tune -> shadow -> promote ->
# recover, no-adaptation control stays broken, rollback under load.


class TestAdaptBenchSelftest:
    def test_selftest_passes(self, tmp_path):
        out = tmp_path / "BENCH_ADAPT_selftest.json"
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "adapt_bench.py"),
             "--selftest", "--out", str(out)],
            capture_output=True, text=True, timeout=900,
            env=dict(os.environ, EEGTPU_NO_LOG_FILE="1",
                     EEGTPU_PLATFORM="cpu"))
        assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
        assert "SELFTEST PASS" in proc.stdout
        record = json.loads(out.read_text())
        rec = record["recovery"]
        assert rec["promotions"] >= 1 and rec["promotion_errors"] == 0
        assert rec["failed_requests"] == 0
        assert rec["journal_order_ok"] is True
        assert rec["recovered_accuracy"] >= 0.55
        assert rec["drifted_accuracy"] < rec["pre_drift_accuracy"]
        # The no-adaptation control proves recovery is causal, not the
        # EMS healing the drift on its own.
        assert record["latency"]["no_adapt_control_accuracy"] < 0.55
        assert record["rollback"]["failed_requests"] == 0
        assert record["rollback"]["digest_restored"] is True
