"""Benchmark: fused TPU fold-training throughput vs the reference's loop style.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
     "platform": ..., "baseline": N, "compile_s": N}
(plus an "error" field when a stage failed — the line is always printed).

The measured quantity is within-subject training throughput in
**fold-epochs/second** — how many (fold x epoch) units of the reference's
within-subject protocol (``/root/reference src/eegnet_repl/train.py:30-148``)
complete per second.  The baseline is the reference's training style: a torch
CPU epoch loop with per-batch host->device dispatch and a per-step
``loss.item()`` sync (``model.py:130-168``), run on an architecture-identical
EEGNet.  ``vs_baseline`` is the speedup ratio (ours / baseline).

Workload shape matches the real protocol: a 576-trial subject pool
(2 sessions x 288 trials of (22 ch, 257 t)), 4 folds trained concurrently via
``vmap`` in one compiled program, batch size 64.

Env knobs: BENCH_SMOKE=1 shrinks epochs for a quick correctness pass;
EEGTPU_PLATFORM=cpu|tpu forces the backend and skips the probe (the site
startup pins ``jax_platforms`` to a tunneled TPU backend, so a plain
JAX_PLATFORMS env var is ignored); BENCH_TPU_PROBE_S overrides the probe
timeout (default 90 s); BENCH_PROBE_RETRIES the probe retry count
(default 2).

Robustness contract (round-1 postmortem): the pinned TPU backend can fail
*or hang* at init, which previously killed the run before any JSON was
printed.  We therefore probe the accelerator in a **subprocess** with a
timeout before this process touches JAX, retry a failed probe (round-2
postmortem: the tunnel's availability is intermittent on the scale of
minutes and a single bad-minute probe cost the round its TPU artifact),
fall back to CPU only when all attempts fail — recording ``probe_result``
/ ``fallback_reason`` diagnostics plus the most recent on-chip headline
(``last_onchip``) in the JSON line so a CPU line is self-explaining — and
wrap everything so one JSON line is printed on any Python-level failure;
a watchdog timer (BENCH_DEADLINE_S, default 1500 s) additionally covers
the probe-to-init race where the backend passes the probe but hangs
during this process's own init (best-effort — a hang that never releases
the GIL can still defeat it).

Compile-cache policy (round-2 verdict): the persistent XLA cache is ON —
a warm cache is the difference between a ~65-470 s headline compile and a
~seconds cache read through the degrading tunnel, i.e. between landing a
TPU number and the watchdog.  Honesty is preserved by *reporting* the
cache state instead of disabling it: ``compile_cache`` is ``off``/
``cold``/``warm:<entries>`` and ``compile_s`` is whatever the warmup call
actually cost under that state.  FLOP/s + MFU fields ground the
workload-relative ratio in hardware utilization (``utils/flops.py``).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

from eegnetreplication_tpu.utils.platform import select_platform_info

_ONCHIP_LAST_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "BENCH_ONCHIP_LAST.json")
_CS_SCALE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "BENCH_CS_SCALE.json")


def _probe_retries() -> int:
    """Probe retry count: 2 by default — ~6 min worst case, converting a
    bad-minute tunnel outage into a bad-quarter-hour one before the CPU
    fallback (round-2 postmortem).  BENCH_SMOKE defaults to 0: a quick
    correctness pass gains nothing from recovering the TPU and must not
    block ~6 min at import with the tunnel down."""
    default = "0" if os.environ.get("BENCH_SMOKE") else "2"
    try:
        return max(0, int(os.environ.get("BENCH_PROBE_RETRIES", default)))
    except ValueError:
        return int(default)


PLATFORM, PROBE_INFO = select_platform_info(retries=_probe_retries())

# Exactly-one-JSON-line guard: whichever of main() / the watchdog acquires
# this first is the sole printer.
import threading  # noqa: E402

_EMIT_ONCE = threading.Lock()

C, T, N_POOL, BATCH = 22, 257, 576, 64
N_FOLDS = 4
# Run-unique salt folded into every timed execution's PRNG keys.  Distinct
# keys per rep defeat WITHIN-run result caching, but the tunneled backend
# was also observed (round 2) replaying results ACROSS bench invocations:
# deterministic keys made rep N of this run byte-identical to rep N of
# yesterday's, and the "measurement" came back in ~4 ms (~112k fold-epochs/s,
# a ~500x overstatement).  Fresh entropy per process makes every submitted
# execution globally unique.
RUN_SALT = int.from_bytes(os.urandom(4), "little")
# The CPU path is the contract-safety fallback, not the measurement of
# record; run it at smoke scale so the JSON line lands well inside the
# watchdog deadline (dress-rehearsed 2026-07-30 on a 1-core host: 10 CPU
# epochs finished with ~1 min to spare against the 1500 s watchdog — 6
# restores a real margin).  When probe retries already burned minutes of
# the budget before falling back, shrink further: the retry time plus the
# full CPU workload would otherwise flirt with the watchdog.
_RETRIES_BURNED = PLATFORM == "cpu" and PROBE_INFO.get("seconds", 0) > 60
EPOCHS = (2 if os.environ.get("BENCH_SMOKE")
          else 100 if PLATFORM != "cpu"
          else 2 if _RETRIES_BURNED else 6)
TORCH_EPOCHS = 1 if os.environ.get("BENCH_SMOKE") or PLATFORM == "cpu" else 6


def _synthetic_pool(seed: int = 0):
    rng = np.random.RandomState(seed)
    x = rng.randn(N_POOL, C, T).astype(np.float32)
    y = rng.randint(0, 4, N_POOL).astype(np.int32)
    return x, y


def _assert_fresh(digests: list[bytes], what: str) -> None:
    """Replay guard: distinct-input executions must yield distinct bytes.

    A broken tunnel was observed (2026-07-30) acknowledging repeat
    executions instantly with stale result buffers; identical digests mean
    the backend replayed a result instead of computing one, and the
    measured rate is fiction.
    """
    if len(set(digests)) < len(digests):
        raise RuntimeError(
            f"backend replayed identical results across {what}; timing "
            "invalid (tunnel result-cache or faulted device)")


def _fold_indices():
    """4-fold split with inner 80/20 train/val, like train.py:70-79."""
    from eegnetreplication_tpu.data.splits import (
        inner_train_val_split,
        kfold_indices,
    )

    folds = []
    for train_val, test in kfold_indices(N_POOL, n_splits=4, seed=42):
        train_ids, val_ids = inner_train_val_split(train_val)
        folds.append((train_ids, val_ids, test))
    return folds


def _time_fused_trainer(pool_x, pool_y, raw_folds, epochs, model_kwargs=None):
    """Shared timing core: (fold-epochs/sec, compile seconds).

    ``raw_folds`` is a list of (train_ids, val_ids, test_ids) over the pool.
    Warmup compiles; timed reps use a DIFFERENT key each time — re-running
    with inputs identical to the warmup lets the tunneled remote backend
    serve a cached result in ~7 ms, inflating round-1-style numbers ~250x.
    Median of 3 honest reps.  ``model_kwargs`` overrides EEGNet fields (the
    reduced-precision stage passes ``precision=None``).
    """
    import jax
    import jax.numpy as jnp

    from eegnetreplication_tpu.models import EEGNet
    from eegnetreplication_tpu.training import (
        init_fold_states,
        make_fold_spec,
        make_multi_fold_trainer,
        make_optimizer,
    )

    train_pad = max(len(f[0]) for f in raw_folds)
    val_pad = max(len(f[1]) for f in raw_folds)
    test_pad = max(len(f[2]) for f in raw_folds)
    n_folds = len(raw_folds)

    model = EEGNet(n_channels=C, n_times=T, **(model_kwargs or {}))
    tx = make_optimizer()
    trainer = make_multi_fold_trainer(
        model, tx, batch_size=BATCH, epochs=epochs, train_pad=train_pad,
        val_pad=val_pad, test_pad=test_pad,
    )
    specs = [
        make_fold_spec(tr, va, te, train_pad=train_pad, val_pad=val_pad,
                       test_pad=test_pad)
        for tr, va, te in raw_folds
    ]
    stacked = jax.tree_util.tree_map(lambda *l: jnp.stack(l), *specs)
    states = init_fold_states(model, tx, n_folds, (C, T))
    pool_x, pool_y = jnp.asarray(pool_x), jnp.asarray(pool_y)

    # Replay-guard digests hash the continuous per-epoch LOSS trajectories,
    # not (only) val accuracies: accuracies are quantized to multiples of
    # 1/n_val, so a degenerate constant-prediction model at smoke scale can
    # legitimately repeat them across distinct keys — losses are f32 sums
    # over differently-shuffled batches and cannot collide for genuine
    # executions (ADVICE r2).
    def _digest(out):
        return (np.asarray(out.val_losses).tobytes()
                + np.asarray(out.train_losses).tobytes())

    base = jax.random.fold_in(jax.random.PRNGKey(0), RUN_SALT)
    t0 = time.perf_counter()
    warm = trainer(pool_x, pool_y, stacked, states,
                   jax.random.split(jax.random.fold_in(base, 0), n_folds))
    # Materialize to host bytes, not just block_until_ready: a broken
    # tunnel was observed acknowledging executions instantly with stale
    # buffers (2026-07-30), and real D2H bytes are the strongest liveness
    # signal available from this side.
    digests = [_digest(warm)]
    compile_s = time.perf_counter() - t0
    rates = []
    for rep in range(1, 4):
        rep_keys = jax.random.split(jax.random.fold_in(base, rep), n_folds)
        t0 = time.perf_counter()
        out = trainer(pool_x, pool_y, stacked, states, rep_keys)
        digests.append(_digest(out))
        rates.append(n_folds * epochs / (time.perf_counter() - t0))
    # Distinct PRNG keys produce distinct epoch shuffles, so genuine
    # executions cannot return identical loss trajectories.
    _assert_fresh(digests, "distinct-key training reps")
    return float(np.median(rates)), compile_s


def bench_tpu(x, y, folds) -> tuple[float, float]:
    """(fold-epochs/sec, compile seconds) of the fused vmapped trainer.

    First TPU compile is the slow part; it is amortized over the 36-fold x
    500-epoch real protocol, so excluded from the rate but reported
    separately as compile_s.
    """
    return _time_fused_trainer(x, y, folds, EPOCHS)


def bench_fold_scale(n_subjects: int = 9, epochs: int = 20) -> dict:
    """Throughput of the REAL protocol scale: 9 subjects x 4 folds fused.

    The headline bench trains 4 folds (one subject); the actual
    within-subject protocol vmaps all 36 folds together.  This measures
    that program and reports fold-epochs/s at scale — the number that shows
    fold-vmapping's near-linear win over the reference's sequential
    36-run loop.  (BENCH_SMOKE runs it at 2 subjects x 1 epoch so the code
    path stays exercised off-TPU.)
    """
    rng = np.random.RandomState(1)
    pool_x = rng.randn(n_subjects * N_POOL, C, T).astype(np.float32)
    pool_y = rng.randint(0, 4, n_subjects * N_POOL).astype(np.int32)

    base_folds = _fold_indices()
    raw_folds = [
        (tr + s * N_POOL, va + s * N_POOL, te + s * N_POOL)
        for s in range(n_subjects)
        for tr, va, te in base_folds
    ]
    rate, compile_s = _time_fused_trainer(pool_x, pool_y, raw_folds, epochs)
    return {"fold36_epochs_per_s": round(rate, 2),
            "fold36_compile_s": round(compile_s, 2),
            "fold36_n_folds": len(raw_folds)}


def bench_precision_modes(x, y, folds) -> dict:
    """Headline workload at the MXU's native bf16-operand precision.

    The headline metric runs the model's parity default (full-f32 MXU
    passes, ``EEGNet.precision="highest"``); this stage measures the same
    workload with backend-default matmul precision (`--precision default` on
    the train CLI).  Known confound, flagged in the emitted record: a
    non-"highest" model also fails the ``supports_fused_eval`` gate, so the
    per-epoch validation passes use the plain conv-pair forward instead of
    the algebraically fused one — the delta vs the headline mixes the
    precision change with that (small: validation is ~1/5 of each epoch's
    batches) eval-kernel change.
    """
    rate, compile_s = _time_fused_trainer(x, y, folds, EPOCHS,
                                          model_kwargs={"precision": None})
    return {"mxu_default_fold_epochs_per_s": round(rate, 2),
            "mxu_default_compile_s": round(compile_s, 2),
            "mxu_default_note": "eval path differs from headline "
                                "(plain vs fused forward); see bench.py"}


def bench_eval_kernels() -> dict:
    """Eval-forward microbench: plain apply vs fused-jnp vs Pallas kernel.

    Measures the standalone inference path (``steps.eval_forward``) the
    Pallas block-1 kernel serves; the fused *training* programs use the jnp
    twin (see ``eval_forward``'s docstring for why).  Each variant runs 3
    reps on distinct inputs (the tunneled backend caches repeat executions).
    """
    import jax
    import jax.numpy as jnp

    from eegnetreplication_tpu.models import EEGNet
    from eegnetreplication_tpu.ops.fused_eegnet import (
        fused_eval_forward,
        probe_pallas,
    )

    model = EEGNet(n_channels=C, n_times=T)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, C, T)),
                           train=False)
    params, bs = variables["params"], variables["batch_stats"]
    pool_rng = np.random.RandomState(RUN_SALT % (2 ** 31))
    pools = [jnp.asarray(pool_rng.randn(N_POOL, C, T), jnp.float32)
             for _ in range(4)]

    plain = jax.jit(lambda xx: model.apply(
        {"params": params, "batch_stats": bs}, xx, train=False))
    variants = {"eval_plain": plain,
                "eval_fused": lambda xx: fused_eval_forward(
                    model, params, bs, xx, use_pallas=False)}
    if probe_pallas(model):
        variants["eval_pallas"] = lambda xx: fused_eval_forward(
            model, params, bs, xx, use_pallas=True)

    out = {}
    for name, fn in variants.items():
        jax.block_until_ready(fn(pools[0]))  # compile
        reps, digests = [], []
        for i in (1, 2, 3):
            t0 = time.perf_counter()
            digests.append(np.asarray(fn(pools[i])).tobytes())  # real D2H
            reps.append(N_POOL / (time.perf_counter() - t0))
        _assert_fresh(digests, f"distinct input pools ({name})")
        out[name + "_trials_per_s"] = round(float(np.median(reps)))
    return out


def bench_torch_reference_style(x, y, folds) -> float:
    """Fold-epochs/sec of the reference's loop: torch CPU, per-batch dispatch.

    Architecture-identical EEGNet trained the way ``model.py:130-148`` does —
    python batch loop, optimizer step per batch, ``loss.item()`` per step —
    sequentially over folds like ``train.py:73``.
    """
    import torch
    import torch.nn as nn

    F1, D = 8, 2
    F2 = F1 * D

    class TorchEEGNet(nn.Module):
        def __init__(self):
            super().__init__()
            self.temporal = nn.Sequential(
                nn.Conv2d(1, F1, (1, 32), padding="same", bias=False),
                nn.BatchNorm2d(F1))
            self.spatial = nn.Sequential(
                nn.Conv2d(F1, F2, (C, 1), groups=F1, bias=False),
                nn.BatchNorm2d(F2), nn.ELU(), nn.AvgPool2d((1, 4)),
                nn.Dropout(0.5))
            self.separable = nn.Sequential(
                nn.Conv2d(F2, F2, (1, 16), groups=F2, padding="same",
                          bias=False),
                nn.Conv2d(F2, F2, (1, 1), bias=False),
                nn.BatchNorm2d(F2), nn.ELU(), nn.AvgPool2d((1, 8)),
                nn.Dropout(0.5), nn.Flatten())
            self.classifier = nn.Linear(F2 * (T // 32), 4)

        def forward(self, inp):
            h = self.separable(self.spatial(self.temporal(inp.unsqueeze(1))))
            return self.classifier(h)

    torch.manual_seed(0)
    tr_idx, va_idx, _ = folds[0]
    xt = torch.from_numpy(x)
    yt = torch.from_numpy(y.astype(np.int64))
    model = TorchEEGNet()
    opt = torch.optim.Adam(model.parameters(), lr=1e-3, eps=1e-7)
    loss_fn = nn.CrossEntropyLoss()

    def one_epoch(epoch_rng):
        model.train()
        order = epoch_rng.permutation(tr_idx)
        for s in range(0, len(order), BATCH):
            b = order[s:s + BATCH]
            opt.zero_grad()
            loss = loss_fn(model(xt[b]), yt[b])
            loss.backward()
            opt.step()
            loss.item()  # the per-step sync of model.py:143
        model.eval()
        with torch.no_grad():
            for s in range(0, len(va_idx), BATCH):
                b = va_idx[s:s + BATCH]
                loss_fn(model(xt[b]), yt[b]).item()

    rng = np.random.RandomState(0)
    one_epoch(rng)  # warmup
    t0 = time.perf_counter()
    for _ in range(TORCH_EPOCHS):
        one_epoch(rng)
    dt = time.perf_counter() - t0
    return TORCH_EPOCHS / dt


def _flops_accounting(timeout_s: float = 420.0) -> dict:
    """Per-unit FLOP counts from XLA's HLO cost model (CPU subprocess).

    Shape-only cost analysis needs no device, but lowering in THIS process
    would target the tunneled backend; a subprocess with the axon startup
    hook disabled behaves identically in every environment and cannot
    perturb the measurement of record.  Returns ``{}`` on any failure —
    the accounting is an add-on, never a gate.
    """
    folds = _fold_indices()
    train_pad = max(len(f[0]) for f in folds)
    val_pad = max(len(f[1]) for f in folds)
    src = (
        "import json\n"
        "from eegnetreplication_tpu.models import EEGNet\n"
        "from eegnetreplication_tpu.training import make_optimizer\n"
        "from eegnetreplication_tpu.utils.flops import (\n"
        "    eval_forward_flops, fold_epoch_flops)\n"
        f"m = EEGNet(n_channels={C}, n_times={T})\n"
        "tx = make_optimizer()\n"
        f"fe = fold_epoch_flops(m, tx, batch_size={BATCH}, "
        f"train_pad={train_pad}, val_pad={val_pad}, "
        f"sample_shape=({C}, {T}))\n"
        f"ev = eval_forward_flops(m, {N_POOL}, ({C}, {T}))\n"
        "print(json.dumps({'fold_epoch_flops': fe, "
        "'eval_forward_flops_pool': ev}))\n"
    )
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""  # skip the axon startup hook entirely
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("EEGTPU_PLATFORM", None)
    try:
        out = subprocess.run(
            [sys.executable, "-c", src], capture_output=True, text=True,
            timeout=timeout_s, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if out.returncode != 0:
            return {}
        counts = json.loads(out.stdout.strip().splitlines()[-1])
        return {k: v for k, v in counts.items() if v}
    except Exception:  # noqa: BLE001 — accounting is best-effort
        return {}


def _add_flops_fields(record: dict, timeout_s: float = 420.0) -> None:
    """Derive achieved-FLOP/s + MFU fields from already-measured rates.

    MFU denominators: the chip's bf16 MXU peak (``utils/flops.py``).  The
    headline runs f32-precision matmuls, which spend extra MXU passes —
    that cost is deliberately visible as lower MFU rather than hidden by a
    precision-specific peak.  CPU runs get FLOP/s only (no meaningful MFU).
    """
    counts = _flops_accounting(timeout_s)
    if not counts:
        record["flops_error"] = "cost analysis unavailable"
        return
    from eegnetreplication_tpu.utils.flops import assumed_peak_flops

    device_kind = None
    if PLATFORM != "cpu":
        try:
            import jax

            device_kind = jax.devices()[0].device_kind
        except Exception:  # noqa: BLE001
            pass
    peak, peak_label = assumed_peak_flops(device_kind)
    on_accel = PLATFORM != "cpu"
    if on_accel:
        record["mfu_peak"] = peak_label

    def emit(prefix: str, rate, flops_per_unit: float) -> None:
        if not rate:
            return
        flops_per_s = rate * flops_per_unit
        record[f"{prefix}_gflops_per_s"] = round(flops_per_s / 1e9, 1)
        if on_accel:
            record[f"{prefix}_mfu_pct"] = round(100 * flops_per_s / peak, 4)

    fe = counts.get("fold_epoch_flops")
    if fe:
        record["fold_epoch_gflops"] = round(fe / 1e9, 3)
        for rate_key, prefix in (("value", "train"),
                                 ("fold36_epochs_per_s", "fold36"),
                                 ("mxu_default_fold_epochs_per_s",
                                  "mxu_default")):
            emit(prefix, record.get(rate_key), fe)
    ev = counts.get("eval_forward_flops_pool")
    if ev:
        for rate_key, prefix in (("eval_fused_trials_per_s", "eval_fused"),
                                 ("eval_pallas_trials_per_s",
                                  "eval_pallas")):
            emit(prefix, record.get(rate_key), ev / N_POOL)


def _compile_cache_state() -> tuple[str, str | None, int]:
    """("off"|"cold"|"warm:<n>", cache_dir, entry count) pre-compile."""
    cache_dir = PROBE_INFO.get("cache_dir")
    if not cache_dir:
        return "off", None, 0
    try:
        entries = len(os.listdir(cache_dir))
    except OSError:
        return "off", None, 0
    return (f"warm:{entries}" if entries else "cold"), cache_dir, entries


def _read_last_onchip() -> dict | None:
    try:
        with open(_ONCHIP_LAST_PATH) as f:
            entry = json.load(f)
        return entry if isinstance(entry, dict) else None
    except Exception:  # noqa: BLE001
        return None


def _read_cs_scale_summary() -> dict | None:
    """Compact summary of the committed cross-subject at-scale measurement
    (``BENCH_CS_SCALE.json``: the reference's full 90-fold x 500-epoch
    protocol run end-to-end on one chip — scripts/cs_at_scale.py).  The run
    takes ~75 min, far beyond the bench watchdog, so the driver artifact
    references the committed record instead of re-measuring."""
    try:
        with open(_CS_SCALE_PATH) as f:
            rec = json.load(f)
        if not (isinstance(rec, dict) and rec.get("ok")):
            return None
        summary = {k: rec.get(k) for k in
                   ("platform", "n_folds", "epochs", "wall_s",
                    "protocol_fold_epochs_per_s", "utc")}
        # Freshness: a live record carries the per-fold min-val-loss vector
        # signal (distinct_fold_val_losses, protocols.py); a record written
        # before that signal existed can only defend itself with the
        # accuracy vector — say so instead of looking silently complete
        # (ADVICE r3).
        if "distinct_fold_val_losses" in rec:
            summary["distinct_fold_val_losses"] = (
                rec["distinct_fold_val_losses"])
        else:
            summary["freshness"] = "record predates val-loss signal"
        # Honest denominator (VERDICT r3 weak #5): quote the CS rate
        # against the measured torch CS baseline — but only when the two
        # records describe the SAME fold workload (the reference trains
        # 5 x trials_per_session pooled Train-session trials per CS fold,
        # train.py:204-215); mismatched shapes would silently corrupt the
        # headline ratio.
        try:
            with open(os.path.join(os.path.dirname(_CS_SCALE_PATH),
                                   "BENCH_CS_BASELINE.json")) as f:
                base = json.load(f)
            rate = summary.get("protocol_fold_epochs_per_s")
            tps = rec.get("trials_per_session")
            if base.get("value") and rate and tps:
                if (base.get("train_trials") == 5 * tps
                        and base.get("val_trials") == 3 * tps):
                    summary["cs_baseline"] = base["value"]
                    summary["cs_vs_baseline"] = round(
                        rate / base["value"], 1)
                else:
                    summary["cs_baseline_note"] = (
                        f"baseline shapes {base.get('train_trials')}/"
                        f"{base.get('val_trials')} != at-scale 5x/3x "
                        f"{tps} — ratio withheld")
        except Exception:  # noqa: BLE001 — add-on only
            pass
        return summary
    except Exception:  # noqa: BLE001 — informational add-on only
        return None


def _attach_last_onchip(record: dict) -> None:
    """On a failed accelerator run, embed the most recent successful
    on-chip headline so the artifact still reports a real measurement.
    No-op for CPU lines (they attach it in main's fallback block), when a
    headline value WAS measured before the failure (attaching an older
    record beside a fresh value would mislead), or when already present."""
    if (record.get("platform") != "cpu" and not record.get("value")
            and "last_onchip" not in record):
        last = _read_last_onchip()
        if last:
            record["last_onchip"] = last


def _write_last_onchip(record: dict) -> None:
    """Persist the headline of a successful on-chip run (best-effort).

    A later CPU-fallback line embeds this as ``last_onchip`` so the
    artifact is self-explaining about what the chip measured most
    recently — informational only, never the headline value.  Written
    through the shared telemetry schema writer (``obs/schema.py``:
    validated envelope + atomic replace) like every other BENCH artifact.
    """
    try:
        from eegnetreplication_tpu.obs import schema as obs_schema

        entry = {
            "value": record.get("value"),
            "unit": record.get("unit"),
            "vs_baseline": record.get("vs_baseline"),
            "platform": record.get("platform"),
            "compile_s": record.get("compile_s"),
            "train_mfu_pct": record.get("train_mfu_pct"),
        }
        obs_schema.write_json_artifact(_ONCHIP_LAST_PATH, entry, kind="bench")
    except Exception:  # noqa: BLE001
        pass


def _arm_watchdog(record: dict, deadline_s: float) -> "threading.Timer":
    """Best-effort guard for hangs the probe can't prevent.

    The subprocess probe validates backend init, but a flaky tunneled
    backend can still hang during THIS process's init (probe-to-init
    race).  If the deadline passes, print the JSON line with an error
    field and hard-exit — rc 0 with the contract honored beats the
    driver's rc-124 timeout with no output.  Best-effort: a hang that
    never releases the GIL can still defeat it.
    """
    def fire():
        if not _EMIT_ONCE.acquire(blocking=False):
            return  # main() is already printing the line
        record["error"] = f"watchdog: bench exceeded {deadline_s:.0f}s"
        _attach_last_onchip(record)  # a hung-tunnel line still reports
        print(json.dumps(record), flush=True)  # the last real measurement
        os._exit(0)

    timer = threading.Timer(deadline_s, fire)
    timer.daemon = True
    timer.start()
    return timer


def _attempt_late_tpu_promotion(record: dict, deadline_s: float,
                                t_start: float) -> None:
    """Re-probe the accelerator after a CPU fallback; promote on success.

    Runs only when (a) this process measured on CPU as a *fallback* (a
    forced EEGTPU_PLATFORM=cpu run means the caller wanted CPU), (b) the
    remaining watchdog budget leaves room for a probe plus a warm-cache
    accelerator run, and (c) BENCH_LATE_REPROBE isn't 0 (the child runs
    with it set to 0 — no recursion).  The child is this same script with
    the platform forced to the probe's answer; forcing skips the child's
    probe and enables the persistent compile cache, so a builder-warmed
    cache finally applies to a driver-invoked run (VERDICT r3 weak #1).
    On success the child's JSON line becomes the headline and the CPU
    measurement is preserved under ``first_attempt_cpu``.
    """
    from eegnetreplication_tpu.utils.platform import probe_accelerator_info

    if (record.get("platform") != "cpu" or PROBE_INFO.get("forced")
            or os.environ.get("BENCH_LATE_REPROBE", "1") == "0"):
        return
    min_child_s = 300.0
    remaining = deadline_s - (time.perf_counter() - t_start)
    probe_s = min(90.0, remaining - min_child_s)
    if probe_s < 30.0:
        record["late_reprobe"] = (
            f"skipped: {remaining:.0f}s of watchdog budget left")
        return
    r = probe_accelerator_info(probe_s, refresh=True)  # bypass cache READ
    diag = {"probe_result": r.get("result"),
            "probe_reason": str(r.get("reason"))[:120]}
    if not r.get("result"):
        record["late_reprobe"] = diag
        return
    # Budget nesting, strictly inside the parent watchdog: the watchdog
    # fires at deadline_s; the subprocess wait must expire BEFORE that so
    # a child hung at backend init (the same flakiness that caused the
    # fallback) is reaped by the keep-CPU-line except path below, not by
    # the watchdog stamping an error onto an already-valid CPU record.
    remaining = deadline_s - (time.perf_counter() - t_start) - 30.0
    env = dict(os.environ, EEGTPU_PLATFORM=str(r["result"]),
               BENCH_LATE_REPROBE="0",
               BENCH_DEADLINE_S=str(int(max(120.0, remaining - 60.0))))
    try:
        child = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            capture_output=True, text=True, timeout=max(150.0, remaining))
        line = next((ln for ln in reversed(child.stdout.splitlines())
                     if ln.startswith("{")), None)
        parsed = json.loads(line) if line else None
    except Exception as exc:  # noqa: BLE001 — keep the CPU line
        record["late_reprobe"] = dict(diag, promoted=False,
                                      child_error=f"{type(exc).__name__}: "
                                                  f"{exc}"[:160])
        return
    # isinstance guard: a child emitting "value": null would make a bare
    # `> 0` raise TypeError, and the caller's blanket except would then
    # clobber the structured probe diagnostics (ADVICE r4).
    if (parsed and parsed.get("platform") not in (None, "cpu")
            and isinstance(parsed.get("value"), (int, float))
            and parsed.get("value") > 0 and not parsed.get("error")):
        cpu_summary = {k: record.get(k) for k in
                       ("value", "vs_baseline", "compile_s",
                        "fallback_reason", "probe_attempts",
                        "probe_seconds")}
        record.clear()
        record.update(parsed)
        record["late_reprobe"] = dict(diag, promoted=True)
        record["first_attempt_cpu"] = cpu_summary
    else:
        tail = (child.stderr or child.stdout or "")[-160:]
        record["late_reprobe"] = dict(
            diag, promoted=False,
            child_error=(parsed or {}).get("error") or tail)


def main() -> None:
    """Run the bench; ALWAYS print exactly one JSON line on stdout."""
    from eegnetreplication_tpu.obs import schema as obs_schema

    record = {
        # Telemetry-schema envelope (obs/schema.py): the stdout line and
        # every BENCH_*.json written from it validate the same way.
        "schema_version": obs_schema.SCHEMA_VERSION,
        "utc": obs_schema.utc_now(),
        "metric": "within_subject_training_throughput",
        "value": 0.0,
        "unit": "fold-epochs/s",
        "vs_baseline": 0.0,
        "platform": PLATFORM,
        "probe_result": PROBE_INFO.get("result"),
        "probe_attempts": PROBE_INFO.get("attempts"),
        "probe_seconds": PROBE_INFO.get("seconds"),
    }
    if PROBE_INFO.get("fallback_reason"):
        record["fallback_reason"] = PROBE_INFO["fallback_reason"]
    if PLATFORM == "cpu":
        last = _read_last_onchip()
        if last:
            record["last_onchip"] = last
        else:
            # No machine-written on-chip record on this host yet; point at
            # the committed measurement log so a fallback line still says
            # where the chip numbers live (informational, not a headline).
            record["onchip_notes"] = (
                "no BENCH_ONCHIP_LAST.json on this host; replay-guarded "
                "chip measurements are recorded in BENCH_NOTES.md")
    cache_state, _cache_dir, _cache_entries = _compile_cache_state()
    record["compile_cache"] = cache_state
    cs_scale = _read_cs_scale_summary()
    if cs_scale:  # the committed protocol-scale measurement (75-min run;
        record["cs_at_scale"] = cs_scale  # far beyond any bench budget)
    try:
        deadline_s = float(os.environ.get("BENCH_DEADLINE_S", "1500"))
    except ValueError:
        deadline_s = 1500.0
    # The driver's external envelope starts at process launch, so probe
    # retry time already spent at import counts against it; arm the
    # watchdog with the REMAINDER or a hung stage would emit its JSON
    # only after the driver's own timeout already killed us.
    deadline_s = max(180.0, deadline_s - float(PROBE_INFO.get("seconds")
                                               or 0.0))
    watchdog = _arm_watchdog(record, deadline_s)
    t_start = time.perf_counter()
    try:
        x, y = _synthetic_pool()
        folds = _fold_indices()
        ours, compile_s = bench_tpu(x, y, folds)
        record.update(value=round(ours, 2), compile_s=round(compile_s, 2))
        if _cache_dir:
            try:  # how many executables the headline compile added
                record["compile_cache_new_entries"] = (
                    len(os.listdir(_cache_dir)) - _cache_entries)
            except OSError:
                pass
        baseline = bench_torch_reference_style(x, y, folds)
        record.update(
            vs_baseline=round(ours / baseline, 2),
            baseline=round(baseline, 2),
        )
        # Late re-probe BEFORE the CPU add-ons (VERDICT r3 item 1): a
        # driver-captured platform:tpu line outranks every CPU-side add-on,
        # and the promoted child record carries its own add-ons.  Runs here,
        # with the headline + baseline safely in hand, so contended CPU
        # add-ons can't starve it of watchdog budget.
        try:
            _attempt_late_tpu_promotion(record, deadline_s, t_start)
        except Exception as exc:  # noqa: BLE001 — promotion is best-effort
            record["late_reprobe"] = (
                f"error: {type(exc).__name__}: {exc}"[:200])
        if (isinstance(record.get("late_reprobe"), dict)
                and record["late_reprobe"].get("promoted")):
            return _emit(record, watchdog)
        try:
            record.update(bench_eval_kernels())
        except Exception as exc:  # noqa: BLE001 — optional add-on: a
            # failure here must not mark the (already valid) main metric
            record["eval_bench_error"] = f"{type(exc).__name__}: {exc}"[:200]
        if os.environ.get("BENCH_SMOKE"):
            try:  # keep the code path exercised off-TPU, at toy scale
                record.update(bench_fold_scale(n_subjects=2, epochs=1))
            except Exception as exc:  # noqa: BLE001 — optional add-on
                record["fold36_error"] = f"{type(exc).__name__}: {exc}"[:200]
        elif PLATFORM != "cpu":
            # Budget guard: the 36-fold compile is the most expensive stage;
            # only start it while at least half the watchdog budget remains,
            # so a slow run degrades to a missing add-on field instead of a
            # watchdog error over an already-valid headline metric.  Runs
            # before the precision stage: fold36 is the older, richer metric
            # and must not be starved by the newer add-on.
            if time.perf_counter() - t_start < 0.5 * deadline_s:
                try:
                    record.update(bench_fold_scale())
                except Exception as exc:  # noqa: BLE001 — optional add-on
                    record["fold36_error"] = (
                        f"{type(exc).__name__}: {exc}"[:200])
            else:
                record["fold36_error"] = "skipped: insufficient time budget"
        if os.environ.get("BENCH_SMOKE") or PLATFORM != "cpu":
            # Same budget-guard pattern: a second full trainer compile must
            # never risk the watchdog firing over a valid headline metric.
            if (os.environ.get("BENCH_SMOKE")
                    or time.perf_counter() - t_start < 0.6 * deadline_s):
                try:  # reduced-precision twin of the headline workload
                    record.update(bench_precision_modes(x, y, folds))
                except Exception as exc:  # noqa: BLE001 — optional add-on
                    record["mxu_default_error"] = (
                        f"{type(exc).__name__}: {exc}"[:200])
            else:
                record["mxu_default_error"] = (
                    "skipped: insufficient time budget")
        # FLOP/s + MFU accounting (VERDICT r2 item 3).  Budget-guarded
        # against the REMAINING watchdog budget (probe retries may already
        # have shrunk deadline_s): the subprocess gets the smaller of its
        # nominal cap and what the watchdog leaves, minus a margin, and is
        # skipped outright when that window is too small to be useful —
        # a cost-analysis add-on must never push an already-valid headline
        # into the watchdog.
        remaining_s = deadline_s - (time.perf_counter() - t_start)
        if os.environ.get("BENCH_SMOKE") or remaining_s > 180.0:
            _add_flops_fields(record,
                              timeout_s=min(420.0, max(120.0,
                                                       remaining_s - 60.0)))
        else:
            record["flops_error"] = "skipped: insufficient time budget"
        if PLATFORM != "cpu" and not record.get("error"):
            _write_last_onchip(record)
    except Exception as exc:  # noqa: BLE001 — contract: always emit the line
        record["error"] = f"{type(exc).__name__}: {exc}"[:300]
        # A mid-run backend death (observed: the tunnel's remote_compile
        # endpoint dropping partway through a stage) leaves an accelerator
        # line with value 0.0; attach the most recent successful on-chip
        # headline so the artifact still reports a real measurement.
        _attach_last_onchip(record)
    _emit(record, watchdog)


def _emit(record: dict, watchdog) -> None:
    if _EMIT_ONCE.acquire(blocking=False):
        watchdog.cancel()
        print(json.dumps(record))


if __name__ == "__main__":
    main()
