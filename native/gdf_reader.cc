// Native GDF reader: the C++ fast path behind eegnetreplication_tpu.data.gdf.
//
// The reference's ingest is MNE's Python GDF parser (it reads each BCI-IV-2a
// recording through mne.io.read_raw_gdf, src/eegnet_repl/dataset.py:86);
// this library parses the same format (GDF v1.x / v2.x, per the GDF spec and
// the BioSig reference implementation) with a single pass over a memory
// buffer, exposed through a C ABI consumed via ctypes
// (eegnetreplication_tpu/data/gdf_native.py).  The Python implementation in
// data/gdf.py documents the layout; the two are cross-checked in
// tests/test_native_gdf.py.
//
// Build: make -C native   (produces build/libeegtpu_gdf.so)

#include <cstdint>
#include <cstring>
#include <cmath>
#include <cstdio>
#include <string>
#include <vector>

namespace {

struct GdfFile {
  int64_t n_channels = 0;
  int64_t n_samples = 0;
  double sfreq = 0.0;
  double version = 0.0;
  std::vector<std::string> labels;
  std::vector<float> signals;      // (n_channels * n_samples) row-major
  std::vector<int64_t> event_pos;  // 0-based samples
  std::vector<int64_t> event_typ;
  std::vector<int64_t> event_dur;
};

template <typename T>
T read_le(const uint8_t* p) {
  T v;
  std::memcpy(&v, p, sizeof(T));
  return v;  // target platforms are little-endian (x86_64 / TPU hosts)
}

// Per-channel sample decoder: GDFTYP -> double.
double decode_sample(const uint8_t* p, uint32_t gdftyp) {
  switch (gdftyp) {
    case 1: return static_cast<double>(read_le<int8_t>(p));
    case 2: return static_cast<double>(read_le<uint8_t>(p));
    case 3: return static_cast<double>(read_le<int16_t>(p));
    case 4: return static_cast<double>(read_le<uint16_t>(p));
    case 5: return static_cast<double>(read_le<int32_t>(p));
    case 6: return static_cast<double>(read_le<uint32_t>(p));
    case 7: return static_cast<double>(read_le<int64_t>(p));
    case 8: return static_cast<double>(read_le<uint64_t>(p));
    case 16: return static_cast<double>(read_le<float>(p));
    case 17: return read_le<double>(p);
    default: return std::nan("");
  }
}

size_t gdftyp_size(uint32_t t) {
  switch (t) {
    case 1: case 2: return 1;
    case 3: case 4: return 2;
    case 5: case 6: case 16: return 4;
    case 7: case 8: case 17: return 8;
    default: return 0;
  }
}

bool fail(char* err, int errlen, const std::string& msg) {
  if (err && errlen > 0) {
    std::snprintf(err, static_cast<size_t>(errlen), "%s", msg.c_str());
  }
  return false;
}

bool parse(const uint8_t* data, size_t size, GdfFile* out, char* err,
           int errlen) {
  if (size < 256) return fail(err, errlen, "truncated GDF file");
  if (std::memcmp(data, "GDF", 3) != 0) return fail(err, errlen, "not a GDF file");

  char ver_buf[6] = {0};
  std::memcpy(ver_buf, data + 4, 4);
  double version = std::atof(ver_buf);
  if (version <= 0.0) return fail(err, errlen, "unparsable GDF version");

  int64_t header_len;
  if (version >= 1.9) {
    header_len = static_cast<int64_t>(read_le<uint16_t>(data + 184)) * 256;
  } else {
    header_len = read_le<int64_t>(data + 184);
  }
  const int64_t n_records = read_le<int64_t>(data + 236);
  const uint32_t dur_num = read_le<uint32_t>(data + 244);
  const uint32_t dur_den = read_le<uint32_t>(data + 248);
  const uint16_t n_channels = read_le<uint16_t>(data + 252);
  if (n_records < 0) return fail(err, errlen, "unknown record count");
  if (header_len < 256 + 256 * static_cast<int64_t>(n_channels) ||
      static_cast<size_t>(header_len) > size) {
    return fail(err, errlen, "bad header length");
  }
  const double record_dur = dur_den ? static_cast<double>(dur_num) / dur_den : 1.0;

  // Channel headers are field-major: all labels, then all transducers, ...
  const uint8_t* ch = data + 256;
  size_t off = 0;
  auto block = [&](size_t per_ch) {
    const uint8_t* p = ch + off;
    off += per_ch * n_channels;
    return p;
  };

  const uint8_t* labels_p = block(16);
  block(80);  // transducer
  const uint8_t *physmin_p, *physmax_p, *digmin_p, *digmax_p;
  bool dig_is_int = false;
  if (version >= 1.9) {
    block(6);   // physical dimension (obsolete)
    block(2);   // physical dimension code
    physmin_p = block(8);
    physmax_p = block(8);
    digmin_p = block(8);
    digmax_p = block(8);
    block(68);  // prefilter text
    block(4); block(4); block(4);  // lowpass / highpass / notch
  } else {
    block(8);   // physical dimension text
    physmin_p = block(8);
    physmax_p = block(8);
    digmin_p = block(8);   // int64 in v1
    digmax_p = block(8);
    dig_is_int = true;
    block(80);  // prefilter text
  }
  const uint8_t* spr_p = block(4);
  const uint8_t* typ_p = block(4);

  std::vector<uint32_t> spr(n_channels), gdftyp(n_channels);
  std::vector<double> gain(n_channels), offset(n_channels);
  std::vector<size_t> samp_size(n_channels);
  for (int c = 0; c < n_channels; ++c) {
    spr[c] = read_le<uint32_t>(spr_p + 4 * c);
    gdftyp[c] = read_le<uint32_t>(typ_p + 4 * c);
    samp_size[c] = gdftyp_size(gdftyp[c]);
    if (samp_size[c] == 0) {
      return fail(err, errlen, "unsupported GDFTYP " + std::to_string(gdftyp[c]));
    }
    const double pmin = read_le<double>(physmin_p + 8 * c);
    const double pmax = read_le<double>(physmax_p + 8 * c);
    const double dmin = dig_is_int
        ? static_cast<double>(read_le<int64_t>(digmin_p + 8 * c))
        : read_le<double>(digmin_p + 8 * c);
    const double dmax = dig_is_int
        ? static_cast<double>(read_le<int64_t>(digmax_p + 8 * c))
        : read_le<double>(digmax_p + 8 * c);
    const double denom = dmax - dmin;
    gain[c] = denom != 0.0 ? (pmax - pmin) / denom : 1.0;
    offset[c] = pmin - gain[c] * dmin;
    if (spr[c] != spr[0]) {
      return fail(err, errlen, "mixed samples-per-record not supported");
    }
  }
  const uint32_t spr0 = n_channels ? spr[0] : 0;

  size_t record_bytes = 0;
  std::vector<size_t> ch_offset(n_channels);
  for (int c = 0; c < n_channels; ++c) {
    ch_offset[c] = record_bytes;
    record_bytes += samp_size[c] * spr0;
  }
  const size_t data_bytes = record_bytes * static_cast<size_t>(n_records);
  if (static_cast<size_t>(header_len) + data_bytes > size) {
    return fail(err, errlen, "truncated data section");
  }

  out->n_channels = n_channels;
  out->n_samples = static_cast<int64_t>(n_records) * spr0;
  out->sfreq = record_dur > 0 ? spr0 / record_dur : spr0;
  out->version = version;
  out->labels.resize(n_channels);
  for (int c = 0; c < n_channels; ++c) {
    const char* l = reinterpret_cast<const char*>(labels_p + 16 * c);
    size_t n = strnlen(l, 16);
    while (n > 0 && (l[n - 1] == ' ')) --n;
    out->labels[c].assign(l, n);
  }

  out->signals.resize(static_cast<size_t>(n_channels) * out->n_samples);
  const uint8_t* body = data + header_len;
  for (int64_t r = 0; r < n_records; ++r) {
    const uint8_t* rec = body + r * record_bytes;
    for (int c = 0; c < n_channels; ++c) {
      const uint8_t* src = rec + ch_offset[c];
      float* dst = out->signals.data() +
                   static_cast<size_t>(c) * out->n_samples + r * spr0;
      const double g = gain[c], o = offset[c];
      if (gdftyp[c] == 16 && g == 1.0 && o == 0.0) {
        std::memcpy(dst, src, sizeof(float) * spr0);  // common fast path
      } else {
        const size_t ss = samp_size[c];
        for (uint32_t s = 0; s < spr0; ++s) {
          dst[s] = static_cast<float>(g * decode_sample(src + s * ss, gdftyp[c]) + o);
        }
      }
    }
  }

  // Event table (optional), after the data records.
  const size_t ev_start = header_len + data_bytes;
  if (ev_start + 8 <= size) {
    const uint8_t* ev = data + ev_start;
    const uint8_t mode = ev[0];
    size_t n_events;
    // 24-bit count + float32 rate only from v1.94 (GDF spec / BioSig);
    // GDF 1.90-1.93 keep the v1 layout (3-byte rate + uint32 count).
    if (version >= 1.94) {
      n_events = ev[1] | (ev[2] << 8) | (static_cast<size_t>(ev[3]) << 16);
    } else {
      n_events = read_le<uint32_t>(ev + 4);
    }
    size_t cursor = 8;
    if (ev_start + cursor + 6 * n_events <= size) {
      out->event_pos.resize(n_events);
      out->event_typ.resize(n_events);
      out->event_dur.assign(n_events, 0);
      for (size_t i = 0; i < n_events; ++i) {
        // GDF positions are 1-based sample indices.
        out->event_pos[i] =
            static_cast<int64_t>(read_le<uint32_t>(ev + cursor + 4 * i)) - 1;
      }
      cursor += 4 * n_events;
      for (size_t i = 0; i < n_events; ++i) {
        out->event_typ[i] = read_le<uint16_t>(ev + cursor + 2 * i);
      }
      cursor += 2 * n_events;
      if (mode == 3 && ev_start + cursor + 6 * n_events <= size) {
        cursor += 2 * n_events;  // per-event channel numbers
        for (size_t i = 0; i < n_events; ++i) {
          out->event_dur[i] = read_le<uint32_t>(ev + cursor + 4 * i);
        }
      }
    }
  }
  return true;
}

}  // namespace

extern "C" {

// Parse `path`; returns an opaque handle or nullptr (error text in `err`).
void* gdf_open(const char* path, char* err, int errlen) {
  std::FILE* f = std::fopen(path, "rb");
  if (!f) {
    fail(err, errlen, std::string("cannot open ") + path);
    return nullptr;
  }
  std::fseek(f, 0, SEEK_END);
  const long fsize = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::vector<uint8_t> buf(static_cast<size_t>(fsize));
  const size_t got = std::fread(buf.data(), 1, buf.size(), f);
  std::fclose(f);
  if (got != buf.size()) {
    fail(err, errlen, "short read");
    return nullptr;
  }
  auto* g = new GdfFile();
  if (!parse(buf.data(), buf.size(), g, err, errlen)) {
    delete g;
    return nullptr;
  }
  return g;
}

void gdf_info(void* h, int64_t* n_channels, int64_t* n_samples, double* sfreq,
              int64_t* n_events, double* version) {
  auto* g = static_cast<GdfFile*>(h);
  *n_channels = g->n_channels;
  *n_samples = g->n_samples;
  *sfreq = g->sfreq;
  *n_events = static_cast<int64_t>(g->event_pos.size());
  *version = g->version;
}

// Copy labels into `out`, one `stride`-byte NUL-terminated slot per channel.
void gdf_labels(void* h, char* out, int64_t stride) {
  auto* g = static_cast<GdfFile*>(h);
  for (int64_t c = 0; c < g->n_channels; ++c) {
    std::snprintf(out + c * stride, static_cast<size_t>(stride), "%s",
                  g->labels[static_cast<size_t>(c)].c_str());
  }
}

// Copy the calibrated (n_channels, n_samples) float32 signal block.
void gdf_signals(void* h, float* out) {
  auto* g = static_cast<GdfFile*>(h);
  std::memcpy(out, g->signals.data(), g->signals.size() * sizeof(float));
}

void gdf_events(void* h, int64_t* pos, int64_t* typ, int64_t* dur) {
  auto* g = static_cast<GdfFile*>(h);
  const size_t n = g->event_pos.size();
  std::memcpy(pos, g->event_pos.data(), n * sizeof(int64_t));
  std::memcpy(typ, g->event_typ.data(), n * sizeof(int64_t));
  std::memcpy(dur, g->event_dur.data(), n * sizeof(int64_t));
}

void gdf_close(void* h) { delete static_cast<GdfFile*>(h); }

}  // extern "C"
